"""Head-node control plane (GCS analog).

Parity with the reference's GCS server (reference:
``src/ray/gcs/gcs_server/gcs_server.h``): node membership + health
(GcsNodeManager / GcsHealthCheckManager), actor registry + scheduling
(GcsActorManager/GcsActorScheduler), placement groups
(GcsPlacementGroupManager), internal KV (GcsInternalKVManager), job table
(GcsJobManager), pubsub, and an aggregated cluster resource view
(GcsResourceManager) that is gossiped back to node agents for spillback
decisions (ray_syncer analog).

One asyncio process, TCP. State is in-memory; a periodic JSON snapshot to
disk provides warm-restart durability (the RedisStoreClient analog).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu._private.config import CONFIG
from ray_tpu._private.protocol import Connection, RpcServer
from ray_tpu._private.resources import (
    NodeResources, ResourceSet, label_constraints_match)

ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


class NodeInfo:
    def __init__(self, node_id: str, addr: Dict, resources: NodeResources,
                 conn: Connection, incarnation: int = 0):
        self.node_id = node_id
        self.addr = addr  # {"host":..., "port":...} of the agent's TCP server
        self.resources = resources
        self.conn = conn
        self.alive = True
        # per-boot monotonic stamp from the agent; fenced on death so a
        # partition survivor re-registering the SAME incarnation is
        # rejected (a fresh agent process carries a higher one)
        self.incarnation = incarnation
        self.last_heartbeat = time.monotonic()
        # set while the agent's connection is down but the reconnect
        # grace window is still open
        self.disconnected_at: Optional[float] = None
        self.labels = resources.labels
        self.pending_demand: List[Dict] = []  # unfulfilled lease requests


class ActorInfo:
    def __init__(self, actor_id: str, spec_wire: Dict, name: str, namespace: str,
                 max_restarts: int, owner_conn: Optional[Connection]):
        self.actor_id = actor_id
        self.spec_wire = spec_wire
        self.name = name
        self.namespace = namespace
        self.state = ACTOR_PENDING
        self.node_id: Optional[str] = None
        self.addr: Optional[Dict] = None  # worker's direct call address
        self.max_restarts = max_restarts
        self.num_restarts = 0
        self.death_cause = ""
        # structured failure provenance: (unix_time, event) transitions +
        # the death's node/incarnation, shipped in every actor event so
        # caller-side ActorDiedError carries the full story
        self.timeline: List = [(time.time(), "created")]
        self.death_node_id: str = ""
        self.death_incarnation: int = 0
        self.owner_conn = owner_conn
        self.owner_job: Optional[str] = None  # job_id of the owning driver
        self.detached = bool(spec_wire.get("detached"))
        self.class_name = spec_wire.get("class_name", "")
        self.pid: int = 0

    def note(self, event: str) -> None:
        self.timeline.append((time.time(), event))
        if len(self.timeline) > 20:  # bounded: restart loops must not grow it
            self.timeline = self.timeline[:1] + self.timeline[-19:]

    def public_view(self) -> Dict:
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "node_id": self.node_id,
            "addr": self.addr,
            "name": self.name,
            "namespace": self.namespace,
            "class_name": self.class_name,
            "num_restarts": self.num_restarts,
            "death_cause": self.death_cause,
            "death_context": {
                "node_id": self.death_node_id or (self.node_id or ""),
                "incarnation": self.death_incarnation,
                "reason": self.death_cause,
                "timeline": [list(ev) for ev in self.timeline],
            },
            "pid": self.pid,
        }


class HeadServer:
    """The cluster brain. All state lives here; agents and drivers connect in."""

    def __init__(self, session_dir: str, port: int = 0,
                 persist_path: Optional[str] = None):
        self.session_dir = session_dir
        self.port = port
        self.server = RpcServer("head")
        self.nodes: Dict[str, NodeInfo] = {}
        # node_id -> highest fenced incarnation: dead incarnations may
        # never rejoin (their leases/objects were already declared lost)
        self.fenced_incarnations: Dict[str, int] = {}
        # loop name -> restart count (ray_tpu_gcs_loop_restarts)
        self.loop_restarts: Dict[str, int] = {}
        self.report_stats = {}
        self.actors: Dict[str, ActorInfo] = {}
        self.named_actors: Dict[tuple, str] = {}  # (namespace, name) -> actor_id
        self.kv: Dict[str, Dict[bytes, bytes]] = {}  # namespace -> key -> value
        self.jobs: Dict[str, Dict] = {}
        self.placement_groups: Dict[str, Dict] = {}
        # (placed_at, ActorInfo) of in-flight placements younger than the
        # gossip window — the anti-double-booking scan reads this instead
        # of every actor in the cluster (O(N^2) across a creation burst)
        self._recent_placements: deque = deque()
        self.subscribers: Dict[str, set] = {}  # channel -> set[Connection]
        self.task_events: List[Dict] = []  # ring buffer of task state transitions
        self.cluster_config = CONFIG.snapshot()
        self._pg_counter = 0
        # GCS fault tolerance (reference: storage backend selected at
        # gcs_server.cc:522-535 — in-memory vs RedisStoreClient HA):
        # durable state goes through a pluggable StoreClient (a file, or
        # an external redis:// store that outlives this head); a restarted
        # head with the same URI resumes KV/jobs/actors/PGs while agents +
        # drivers re-register through their watchdogs
        # (NodeManagerService.NotifyGCSRestart analog).
        self.persist_path = persist_path
        self.store = None
        if persist_path:
            from ray_tpu._private.store_client import create_store_client

            self.store = create_store_client(persist_path)
        self._save_pending = False
        self._save_lock = asyncio.Lock()
        self._driver_conns: Dict[Optional[str], Connection] = {}
        if self.store is not None:
            self._load_state()
        # Strong refs to background tasks: the loop only holds weak refs, so
        # an unreferenced retry task can be GC'd mid-flight (asyncio docs).
        self._bg_tasks: set = set()
        self._register_routes()

    # ------------------------------------------------------- persistence
    def _load_state(self) -> None:
        import pickle

        # A load failure must be FATAL, not "start empty": the next
        # debounced save would overwrite the durable store with an empty
        # snapshot, destroying exactly the state HA exists to protect
        # (e.g. a transient redis outage during head restart).
        tables = self.store.load()
        if tables and all(isinstance(v, bytes) for v in tables.values()):
            state = {name: pickle.loads(blob)
                     for name, blob in tables.items()}
        else:
            # legacy file snapshot: one pickle of the state dict itself
            state = tables
        if not state:
            return
        self.kv = state.get("kv", {})
        self.jobs = state.get("jobs", {})
        self.named_actors = {tuple(k): v for k, v in
                             state.get("named_actors", [])}
        self.placement_groups = state.get("placement_groups", {})
        self._pg_counter = state.get("pg_counter", 0)
        for rec in state.get("actors", []):
            info = ActorInfo(rec["actor_id"], rec["spec_wire"],
                             rec["name"], rec["namespace"],
                             rec["max_restarts"], None)
            info.state = rec["state"]
            info.addr = rec["addr"]
            info.node_id = rec["node_id"]
            info.num_restarts = rec["num_restarts"]
            info.owner_job = rec.get("owner_job")
            self.actors[rec["actor_id"]] = info

    def _schedule_save(self) -> None:
        if self.store is None or self._save_pending:
            return
        self._save_pending = True
        loop = asyncio.get_running_loop()
        loop.call_later(
            CONFIG.head_save_debounce_s,
            lambda: self._hold_task(loop.create_task(
                self._save_state_async())))

    def _snapshot(self) -> Dict:
        """Shallow-copied state snapshot, built on the loop thread so the
        (possibly large) pickle+write can run off-loop without racing
        concurrent mutation."""
        return {
            "kv": {ns: dict(table) for ns, table in self.kv.items()},
            "jobs": {k: dict(v) for k, v in self.jobs.items()},
            "named_actors": [[list(k), v]
                             for k, v in self.named_actors.items()],
            "placement_groups": {k: dict(v)
                                 for k, v in self.placement_groups.items()},
            "pg_counter": self._pg_counter,
            "actors": [
                {"actor_id": a.actor_id, "spec_wire": a.spec_wire,
                 "name": a.name, "namespace": a.namespace,
                 "max_restarts": a.max_restarts,
                 "state": a.state, "addr": a.addr, "node_id": a.node_id,
                 "num_restarts": a.num_restarts, "owner_job": a.owner_job}
                for a in self.actors.values()
            ],
        }

    async def _save_state_async(self) -> None:
        self._save_pending = False
        if self.store is None:
            return
        # serialize writers: a second debounced save during a slow write
        # must not race the same backend
        async with self._save_lock:
            state = self._snapshot()
            await asyncio.to_thread(self._write_snapshot, state)

    def _write_snapshot(self, state: Dict) -> None:
        import pickle

        self.store.save({name: pickle.dumps(value)
                         for name, value in state.items()})

    def _save_state(self) -> None:
        """Synchronous save (shutdown/teardown paths)."""
        if self.store is not None:
            self._write_snapshot(self._snapshot())

    def _hold_task(self, task: "asyncio.Task") -> "asyncio.Task":
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    # ------------------------------------------------------------------ boot
    async def start(self) -> int:
        self.port = await self.server.start_tcp("0.0.0.0", self.port)
        self.server.set_disconnect_handler(self._on_disconnect)
        loop = asyncio.get_running_loop()
        for name, factory in (
                ("health_check", self._health_check_loop),
                ("broadcast", self._broadcast_loop),
                ("metrics", self._metrics_loop)):
            self._hold_task(loop.create_task(self._supervise(name, factory)))
        return self.port

    async def _supervise(self, name: str, factory) -> None:
        """Restart-on-crash supervisor for the head's background loops. A
        bare create_task'd loop that raises (one bad node record, one
        psutil hiccup) would otherwise silently stop health checking /
        gossip FOREVER — the cluster keeps accepting work while dead
        nodes stay 'alive'. Crashes are logged, counted
        (ray_tpu_gcs_loop_restarts), and restarted with a short backoff
        so a deterministic crash can't spin the head at 100% CPU."""
        import logging

        delay = 0.1
        while True:
            try:
                await factory()
                return  # a loop that RETURNS chose to stop; respect it
            except asyncio.CancelledError:
                raise
            except Exception:
                self.loop_restarts[name] = self.loop_restarts.get(name, 0) + 1
                logging.getLogger("ray_tpu").exception(
                    "head background loop %r crashed (restart #%d)",
                    name, self.loop_restarts[name])
                from ray_tpu._private.event import report_event

                try:
                    report_event("ERROR", "GCS_LOOP_CRASH",
                                 f"head loop {name} crashed; restarting",
                                 loop=name,
                                 restarts=self.loop_restarts[name])
                except Exception:
                    pass
                await asyncio.sleep(delay)
                delay = min(delay * 2, 5.0)

    def _register_routes(self) -> None:
        r = self.server.add_handler
        r("RegisterNode", self._register_node)
        r("UpdateResources", self._update_resources)
        r("GetReportStats", self._get_report_stats)
        r("GetClusterView", self._get_cluster_view)
        r("RegisterDriver", self._register_driver)
        r("KvPut", self._kv_put)
        r("KvGet", self._kv_get)
        r("KvDel", self._kv_del)
        r("KvKeys", self._kv_keys)
        r("KvExists", self._kv_exists)
        r("CreateActor", self._create_actor)
        r("ActorReady", self._actor_ready)
        r("ActorDied", self._actor_died)
        r("GetActor", self._get_actor)
        r("GetNamedActor", self._get_named_actor)
        r("ListActors", self._list_actors)
        r("KillActor", self._kill_actor)
        r("ListNodes", self._list_nodes)
        r("Subscribe", self._subscribe)
        r("Publish", self._publish)
        r("CreatePlacementGroup", self._create_placement_group)
        r("RemovePlacementGroup", self._remove_placement_group)
        r("GetPlacementGroup", self._get_placement_group)
        r("ListPlacementGroups", self._list_placement_groups)
        r("ReportTaskEvents", self._report_task_events)
        r("ListTaskEvents", self._list_task_events)
        r("RegisterJob", self._register_job)
        r("ListJobs", self._list_jobs)
        r("DrainNode", self._drain_node)
        r("Ping", self._ping)

    async def _ping(self, conn, p) -> Dict:
        return {"ok": True}

    # ------------------------------------------------------ node membership
    async def _register_node(self, conn: Connection, p: Dict) -> Dict:
        node_id = p["node_id"]
        incarnation = int(p.get("incarnation", 0))
        # fencing: this incarnation was declared dead (its actors were
        # failed over, its leases voided). Letting it back in after the
        # partition heals would resurrect zombie state — reject, and the
        # agent self-terminates on seeing the verdict.
        if CONFIG.node_fence_enabled and \
                incarnation <= self.fenced_incarnations.get(node_id, -1):
            from ray_tpu._private.event import report_event

            report_event("WARNING", "NODE_FENCED",
                         f"rejected re-register of fenced node "
                         f"{node_id[:12]} (incarnation {incarnation})",
                         node_id=node_id, incarnation=incarnation)
            return {"fenced": True, "node_id": node_id,
                    "incarnation": incarnation,
                    "fenced_incarnation":
                        self.fenced_incarnations.get(node_id, -1)}
        existing = self.nodes.get(node_id)
        if existing is not None and existing.alive:
            if existing.incarnation == incarnation:
                # same boot reconnecting (head restart / TCP blip inside
                # the grace window): adopt the new connection in place —
                # the node never died, so no removed/added events fire
                existing.conn = conn
                existing.addr = p["addr"]
                existing.resources = NodeResources.from_wire(p["resources"])
                existing.labels = existing.resources.labels
                existing.last_heartbeat = time.monotonic()
                existing.disconnected_at = None
                conn.meta["node_id"] = node_id
                conn.meta["role"] = "agent"
                return {"cluster_config": self.cluster_config,
                        "cluster_view": self._cluster_view()}
            # a NEWER boot superseding a still-"alive" record (the old
            # agent crashed; its grace window hasn't expired): the old
            # incarnation must die properly — fail its actors over and
            # fence it — or they'd sit ALIVE with a stale addr forever
            await self._mark_node_dead(
                existing, f"superseded by incarnation {incarnation}")
        info = NodeInfo(node_id, p["addr"],
                        NodeResources.from_wire(p["resources"]), conn,
                        incarnation=incarnation)
        self.nodes[node_id] = info
        conn.meta["node_id"] = node_id
        conn.meta["role"] = "agent"
        await self._publish_event("node", {"event": "added", "node_id": node_id,
                                           "addr": p["addr"],
                                           "incarnation": incarnation})
        return {"cluster_config": self.cluster_config,
                "cluster_view": self._cluster_view()}

    async def _register_driver(self, conn: Connection, p: Dict) -> Dict:
        conn.meta["role"] = "driver"
        job_id = p.get("job_id")
        conn.meta["job_id"] = job_id
        # re-registration (driver watchdog after a head restart / link
        # blip): move actor ownership onto the new connection so the old
        # connection's disconnect can't reap them
        old_conn = self._driver_conns.get(job_id)
        for actor in self.actors.values():
            if actor.owner_conn is old_conn and old_conn is not None \
                    and old_conn is not conn:
                actor.owner_conn = conn
            elif actor.owner_conn is None and actor.owner_job and \
                    actor.owner_job == job_id:
                # restored from a snapshot: re-adopt so driver-exit
                # cleanup reaches these actors again
                actor.owner_conn = conn
        self._driver_conns[job_id] = conn
        existing = self.jobs.get(job_id or "")
        if existing is not None and existing.get("state") == "RUNNING":
            pass  # keep original start_time on re-register
        else:
            self.jobs[job_id or ""] = {
                "job_id": job_id, "start_time": time.time(),
                "state": "RUNNING", "entrypoint": p.get("entrypoint", ""),
            }
        self._schedule_save()
        return {"cluster_config": self.cluster_config,
                "cluster_view": self._cluster_view()}

    async def _update_resources(self, conn: Connection, p: Dict) -> None:
        node = self.nodes.get(p["node_id"])
        if node is None:
            return
        node.last_heartbeat = time.monotonic()
        if p.get("hb"):
            # unchanged-view heartbeat (versioned delta gossip): liveness
            # only, no payload to apply
            self.report_stats["heartbeats"] = \
                self.report_stats.get("heartbeats", 0) + 1
            return
        self.report_stats["full_reports"] = \
            self.report_stats.get("full_reports", 0) + 1
        node.resources = NodeResources.from_wire(p["resources"])
        node.pending_demand = p.get("pending", [])

    async def _get_report_stats(self, conn: Connection, p) -> Dict:
        return dict(self.report_stats)

    def _cluster_view(self) -> Dict:
        return {
            nid: {"addr": n.addr, "resources": n.resources.to_wire(),
                  "alive": n.alive, "pending": n.pending_demand}
            for nid, n in self.nodes.items() if n.alive
        }

    async def _get_cluster_view(self, conn: Connection, p) -> Dict:
        return self._cluster_view()

    async def _list_nodes(self, conn: Connection, p) -> List[Dict]:
        return [
            {"node_id": nid, "addr": n.addr, "alive": n.alive,
             "resources_total": n.resources.total.to_wire(),
             "resources_available": n.resources.available.to_wire(),
             "labels": n.labels}
            for nid, n in self.nodes.items()
        ]

    async def _drain_node(self, conn: Connection, p: Dict) -> Dict:
        node = self.nodes.get(p["node_id"])
        if node and node.alive:
            await node.conn.push("Drain", {})
        return {"ok": True}

    async def _health_check_loop(self) -> None:
        period = CONFIG.health_check_period_ms / 1000
        threshold = CONFIG.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_heartbeat > period * threshold:
                    await self._mark_node_dead(node, "health check timeout")

    async def _mark_node_dead(self, node: NodeInfo, reason: str) -> None:
        if not node.alive:
            return
        node.alive = False
        if CONFIG.node_fence_enabled:
            # fence THIS incarnation: a later re-register from it (the
            # partition healed) is rejected; a fresh boot (higher
            # incarnation) may rejoin under the same node_id
            self.fenced_incarnations[node.node_id] = max(
                self.fenced_incarnations.get(node.node_id, -1),
                node.incarnation)
        from ray_tpu._private.event import report_event

        report_event("ERROR", "NODE_DEAD",
                     f"node {node.node_id[:12]} marked dead: {reason}",
                     node_id=node.node_id, reason=reason)
        # drop the node's published system metrics: a dead node's last
        # cpu/mem/TPU gauges must not keep exporting as current
        metrics_ns = self.kv.get("_metrics")
        if metrics_ns:
            prefix = f"metrics::{node.node_id}".encode()
            for key in [k for k in metrics_ns if bytes(k).startswith(prefix)]:
                metrics_ns.pop(key, None)
        removed_msg = {"event": "removed", "node_id": node.node_id,
                       "reason": reason, "incarnation": node.incarnation,
                       "addr": node.addr, "time": time.time()}
        await self._publish_event("node", removed_msg)
        # fail-fast fan-out to the surviving agents (they don't subscribe
        # to pubsub channels): each drops its cached channels to the dead
        # peer so in-flight pulls/leases fail NOW instead of waiting out
        # chunk/RPC deadlines on a black-holed socket
        for other in list(self.nodes.values()):
            if other.alive and other is not node:
                try:
                    await other.conn.push("NodeRemoved", removed_msg)
                except Exception:
                    pass
        # Every actor on that node dies with it.
        for actor in list(self.actors.values()):
            if actor.node_id == node.node_id and actor.state in (
                ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING,
            ):
                actor.death_node_id = node.node_id
                actor.death_incarnation = node.incarnation
                actor.note(f"node {node.node_id[:12]} died: {reason}")
                await self._handle_actor_failure(actor, f"node died: {reason}")

    async def _metrics_loop(self) -> None:
        """Publish head-level system gauges into the same KV pipeline the
        agents' node stats ride (reference: src/ray/stats/metric_defs.cc
        gcs_* series — actor/node/PG/job counts from the control plane)."""
        import json as _json

        from ray_tpu._private.protocol import STATS as _rpc_stats
        from ray_tpu.util.metrics import make_gauge_snapshot as g

        period = max(CONFIG.metrics_report_interval_ms, 1000) / 1000
        while True:
            await asyncio.sleep(period)
            try:
                actor_states: Dict[str, int] = {}
                for a in self.actors.values():
                    actor_states[a.state] = actor_states.get(a.state, 0) + 1
                snaps = [
                    g("ray_tpu_gcs_nodes_alive", "Registered alive nodes.",
                      sum(1 for n in self.nodes.values() if n.alive)),
                    g("ray_tpu_gcs_nodes_dead", "Nodes marked dead.",
                      sum(1 for n in self.nodes.values() if not n.alive)),
                    g("ray_tpu_gcs_placement_groups",
                      "Placement groups registered.",
                      len(self.placement_groups)),
                    g("ray_tpu_gcs_jobs", "Jobs tracked by the head.",
                      len(self.jobs)),
                    g("ray_tpu_gcs_kv_entries",
                      "Internal-KV entries across namespaces.",
                      sum(len(ns) for ns in self.kv.values())),
                    g("ray_tpu_gcs_task_events_buffered",
                      "Task state-transition events held in the ring.",
                      len(self.task_events)),
                    g("ray_tpu_gcs_named_actors",
                      "Named actors registered.", len(self.named_actors)),
                    g("ray_tpu_gcs_driver_connections",
                      "Driver connections attached to the head.",
                      len(self._driver_conns)),
                    g("ray_tpu_gcs_pubsub_channels",
                      "Pubsub channels with at least one subscriber.",
                      sum(1 for s in self.subscribers.values() if s)),
                    g("ray_tpu_gcs_pubsub_subscriptions",
                      "Total (channel, subscriber) pairs.",
                      sum(len(s) for s in self.subscribers.values())),
                    g("ray_tpu_gcs_loop_restarts",
                      "Supervised head background-loop crash restarts.",
                      sum(self.loop_restarts.values())),
                    g("ray_tpu_gcs_nodes_fenced",
                      "Node incarnations fenced after death verdicts.",
                      len(self.fenced_incarnations)),
                    g("ray_tpu_rpc_frames_in_total",
                      "Control-plane frames received by the head.",
                      _rpc_stats["frames_in"]),
                    g("ray_tpu_rpc_frames_out_total",
                      "Control-plane frames sent by the head.",
                      _rpc_stats["frames_out"]),
                    g("ray_tpu_rpc_bytes_in_total",
                      "Control-plane bytes received by the head.",
                      _rpc_stats["bytes_in"]),
                    g("ray_tpu_rpc_bytes_out_total",
                      "Control-plane bytes sent by the head.",
                      _rpc_stats["bytes_out"]),
                ]
                for state, count in actor_states.items():
                    snaps.append(g(
                        "ray_tpu_gcs_actors",
                        "Actors registered, by lifecycle state.",
                        count, {"state": state}))
                ns = self.kv.setdefault("_metrics", {})
                ns[b"metrics::head::gcs"] = _json.dumps(snaps).encode()
            except Exception:
                pass  # metrics must never take the head down

    async def _broadcast_loop(self) -> None:
        """Gossip the cluster resource view to all agents (ray_syncer analog)."""
        period = max(CONFIG.gossip_period_ms, 50) / 1000
        while True:
            await asyncio.sleep(period)
            view = self._cluster_view()
            for node in list(self.nodes.values()):
                if node.alive:
                    await node.conn.push("ClusterView", view)

    async def _on_disconnect(self, conn: Connection) -> None:
        # identity checks: a watchdog reconnect replaces the registered
        # connection; the stale connection's disconnect must not kill the
        # freshly re-registered node/driver
        node_id = conn.meta.get("node_id")
        if node_id and node_id in self.nodes and \
                self.nodes[node_id].conn is conn:
            node = self.nodes[node_id]
            grace = float(CONFIG.node_disconnect_grace_s)
            if grace <= 0 or not node.alive:
                await self._mark_node_dead(node, "agent disconnected")
            elif node.disconnected_at is None:
                # reconnect grace: one lost TCP connection is not a dead
                # node — give the agent's watchdog a window to re-register
                # before its actors are failed over. The heartbeat budget
                # (health check loop) still bounds a SILENT node's
                # lifetime, so grace only shortens nothing and saves
                # healthy nodes from transient blips.
                node.disconnected_at = time.monotonic()
                self._hold_task(asyncio.get_running_loop().create_task(
                    self._disconnect_grace(node, conn, grace)))
        if conn.meta.get("role") == "driver":
            job_id = conn.meta.get("job_id")
            if self._driver_conns.get(job_id) is conn:
                self._driver_conns.pop(job_id, None)
                if job_id in self.jobs:
                    self.jobs[job_id]["state"] = "FINISHED"
                # Non-detached actors owned by this driver die with it.
                for actor in list(self.actors.values()):
                    if actor.owner_conn is conn and not actor.detached \
                            and actor.state != ACTOR_DEAD:
                        await self._kill_actor_internal(
                            actor, "owner driver exited")
        for subs in self.subscribers.values():
            subs.discard(conn)

    async def _disconnect_grace(self, node: NodeInfo, old_conn: Connection,
                                grace: float) -> None:
        await asyncio.sleep(grace)
        current = self.nodes.get(node.node_id)
        if current is not node or not node.alive:
            return  # replaced by a fresh boot, or already dead
        if node.conn is not old_conn or node.disconnected_at is None:
            return  # re-registered within the window
        await self._mark_node_dead(
            node, f"agent disconnected (no re-register within {grace:g}s "
                  "grace)")

    # ------------------------------------------------------------------- kv
    async def _kv_put(self, conn, p) -> bool:
        ns = self.kv.setdefault(p.get("ns", "default"), {})
        key = p["key"]
        if p.get("overwrite", True) or key not in ns:
            ns[key] = p["value"]
            self._schedule_save()
            return True
        return False

    async def _kv_get(self, conn, p):
        return self.kv.get(p.get("ns", "default"), {}).get(p["key"])

    async def _kv_del(self, conn, p) -> int:
        ns = self.kv.get(p.get("ns", "default"), {})
        if p.get("prefix"):
            keys = [k for k in ns if k.startswith(p["key"])]
            for k in keys:
                del ns[k]
            self._schedule_save()
            return len(keys)
        n = 1 if ns.pop(p["key"], None) is not None else 0
        if n:
            self._schedule_save()
        return n

    async def _kv_keys(self, conn, p) -> List[bytes]:
        ns = self.kv.get(p.get("ns", "default"), {})
        prefix = p.get("prefix", b"")
        return [k for k in ns if k.startswith(prefix)]

    async def _kv_exists(self, conn, p) -> bool:
        return p["key"] in self.kv.get(p.get("ns", "default"), {})

    # --------------------------------------------------------------- actors
    async def _create_actor(self, conn: Connection, p: Dict) -> Dict:
        spec = p["spec"]
        actor_id = p["actor_id"]
        name = p.get("name", "")
        namespace = p.get("namespace", "default")
        if name:
            existing_id = self.named_actors.get((namespace, name))
            if existing_id:
                existing = self.actors.get(existing_id)
                if existing and existing.state != ACTOR_DEAD:
                    if p.get("get_if_exists"):
                        return {"existing": existing.public_view()}
                    raise ValueError(f"actor name '{name}' already taken")
        info = ActorInfo(actor_id, spec, name, namespace,
                         p.get("max_restarts", 0), conn)
        info.owner_job = conn.meta.get("job_id")
        self.actors[actor_id] = info
        if name:
            self.named_actors[(namespace, name)] = actor_id
        self._schedule_save()
        ok = await self._schedule_actor(info)
        if not ok:
            # No feasible node right now; keep PENDING and retry when nodes join
            self._hold_task(asyncio.get_running_loop().create_task(
                self._retry_schedule(info)))
        return {"actor_id": actor_id, "state": info.state}

    async def _schedule_actor(self, info: ActorInfo) -> bool:
        """Pick the least-utilized feasible node (GcsActorScheduler analog)."""
        request = ResourceSet.from_wire(info.spec_wire.get("resources", {}))
        strategy = info.spec_wire.get("scheduling_strategy")
        pg = info.spec_wire.get("pg")  # [pg_id, bundle_index] or None
        pg_node: Optional[str] = None
        if pg:
            group = self.placement_groups.get(pg[0])
            if not group or group["state"] == "REMOVED":
                await self._handle_actor_death(
                    info, f"placement group {pg[0]} removed")
                return True
            if group["state"] != "CREATED":
                return False  # PENDING: _retry_schedule polls us again
            if pg[1] is None or pg[1] < 0:
                # bundle_index -1 = any bundle: round-robin over the group's
                # nodes; the agent maps onto a concrete local bundle.
                rr = group.get("rr", 0)
                group["rr"] = rr + 1
                pg_node = group["placement"][rr % len(group["placement"])]
            else:
                pg_node = group["placement"][pg[1]]
        candidates = []
        for node in self.nodes.values():
            if not node.alive:
                continue
            if pg_node is not None and node.node_id != pg_node:
                continue
            if strategy and strategy.get("type") == "node_affinity":
                if node.node_id != strategy.get("node_id"):
                    continue
            if strategy and strategy.get("type") == "node_label":
                if not label_constraints_match(
                        node.labels, strategy.get("hard") or {}):
                    continue
            if pg_node is None and not request.feasible_on(node.resources.total):
                continue
            candidates.append(node)
        if not candidates:
            return False
        # count resources already committed to in-flight actor placements
        # against each candidate: a burst of actor creations scheduled off
        # the same gossip snapshot must not all pick the same node
        # (reference: GcsActorScheduler tracks leased resources per node).
        # Only RECENT placements count — once the target agent's next
        # resource report lands (~one gossip period), its advertised
        # availability already reflects the allocation. The recency window
        # is tracked in a deque so a 1,000-actor burst scans a handful of
        # entries per placement instead of every actor in the cluster
        # (that full scan was O(N^2) across the burst).
        committed: Dict[str, ResourceSet] = {}
        now = time.monotonic()
        window = max(1.5, 3 * CONFIG.gossip_period_ms / 1000.0)
        recent = self._recent_placements
        while recent and now - recent[0][0] > window:
            recent.popleft()
        # dedupe by actor: a retried placement appends a second entry for
        # the same (mutated) ActorInfo — counting both would double-book
        # its request against its current node
        latest = {}
        for placed_at, other in recent:
            latest[id(other)] = other
        for other in latest.values():
            if other is info or other.node_id is None:
                continue
            if other.state not in (ACTOR_PENDING, ACTOR_RESTARTING):
                continue
            req = ResourceSet.from_wire(
                other.spec_wire.get("resources", {}))
            agg = committed.setdefault(other.node_id, ResourceSet({}))
            agg.add(req)

        def effective_available(n):
            avail = n.resources.available.copy()
            pending = committed.get(n.node_id)
            if pending is not None:
                avail.subtract(pending, allow_negative=True)
            return avail

        fits = [n for n in candidates
                if request.fits(effective_available(n))]
        pool = fits or candidates
        if strategy and strategy.get("type") == "node_label":
            soft = strategy.get("soft") or {}
            pool.sort(key=lambda n: (
                not label_constraints_match(n.labels, soft),
                n.resources.utilization()))
        else:
            pool.sort(key=lambda n: n.resources.utilization())
        node = pool[0]
        if node.conn.closed:
            # mid-grace-window: the agent's connection is down and push()
            # would silently no-op — the StartActor frame would be LOST
            # and the actor wedged PENDING with no retry task. Report
            # failure so _retry_schedule keeps polling until the agent
            # re-registers (or the grace expires and the node dies).
            return False
        info.node_id = node.node_id
        info.placed_at = time.monotonic()
        self._recent_placements.append((info.placed_at, info))
        try:
            await node.conn.push("StartActor", {"spec": info.spec_wire,
                                                "actor_id": info.actor_id})
        except Exception:
            return False
        return True

    async def _retry_schedule(self, info: ActorInfo) -> None:
        deadline = time.monotonic() + CONFIG.actor_creation_timeout_ms / 1000
        while time.monotonic() < deadline:
            await asyncio.sleep(1.0)
            if info.state != ACTOR_PENDING and info.state != ACTOR_RESTARTING:
                return
            if await self._schedule_actor(info):
                return
        if info.state in (ACTOR_PENDING, ACTOR_RESTARTING):
            await self._handle_actor_death(info, "no feasible node for actor resources")

    async def _actor_ready(self, conn: Connection, p: Dict) -> None:
        info = self.actors.get(p["actor_id"])
        if not info:
            return
        info.state = ACTOR_ALIVE
        info.addr = p["addr"]
        info.pid = p.get("pid", 0)
        info.node_id = conn.meta.get("node_id", info.node_id)
        # ActorReady arrives on the WORKER's head connection (no node_id
        # in conn.meta) — note after the node_id fallback above resolves
        info.note(f"alive on {(info.node_id or '?')[:12]}")
        self._schedule_save()
        await self._publish_event("actor", info.public_view())

    async def _actor_died(self, conn: Connection, p: Dict) -> None:
        info = self.actors.get(p["actor_id"])
        if not info or info.state == ACTOR_DEAD:
            return
        await self._handle_actor_failure(info, p.get("reason", "worker died"))

    async def _handle_actor_failure(self, info: ActorInfo, reason: str) -> None:
        from ray_tpu._private.event import report_event

        report_event("WARNING", "ACTOR_FAILURE",
                     f"actor {info.actor_id[:12]} ({info.class_name}) "
                     f"failed: {reason}",
                     actor_id=info.actor_id, reason=reason,
                     restarts=info.num_restarts)
        if info.num_restarts < info.max_restarts or info.max_restarts == -1:
            info.num_restarts += 1
            info.state = ACTOR_RESTARTING
            info.note(f"restarting (#{info.num_restarts}): {reason}")
            info.addr = None
            await self._publish_event("actor", info.public_view())
            if not await self._schedule_actor(info):
                self._hold_task(asyncio.get_running_loop().create_task(
                self._retry_schedule(info)))
        else:
            await self._handle_actor_death(info, reason)

    async def _handle_actor_death(self, info: ActorInfo, reason: str) -> None:
        info.state = ACTOR_DEAD
        info.death_cause = reason
        info.note(f"dead: {reason}")
        info.addr = None
        if (info.namespace, info.name) in self.named_actors:
            if self.named_actors[(info.namespace, info.name)] == info.actor_id:
                del self.named_actors[(info.namespace, info.name)]
        self._schedule_save()
        await self._publish_event("actor", info.public_view())

    async def _get_actor(self, conn, p) -> Optional[Dict]:
        info = self.actors.get(p["actor_id"])
        return info.public_view() if info else None

    async def _get_named_actor(self, conn, p) -> Optional[Dict]:
        actor_id = self.named_actors.get((p.get("namespace", "default"), p["name"]))
        if actor_id is None:
            return None
        return self.actors[actor_id].public_view()

    async def _list_actors(self, conn, p) -> List[Dict]:
        return [a.public_view() for a in self.actors.values()]

    async def _kill_actor(self, conn, p) -> Dict:
        info = self.actors.get(p["actor_id"])
        if not info:
            return {"ok": False}
        if p.get("no_restart", True):
            info.max_restarts = info.num_restarts  # suppress further restarts
        await self._kill_actor_internal(info, "ray_tpu.kill")
        return {"ok": True}

    async def _kill_actor_internal(self, info: ActorInfo, reason: str) -> None:
        node = self.nodes.get(info.node_id) if info.node_id else None
        if node and node.alive:
            await node.conn.push("KillActorWorker", {"actor_id": info.actor_id})
        await self._handle_actor_death(info, reason)

    # --------------------------------------------------------------- pubsub
    async def _subscribe(self, conn: Connection, p) -> bool:
        for channel in p["channels"]:
            self.subscribers.setdefault(channel, set()).add(conn)
        return True

    async def _publish(self, conn: Connection, p) -> int:
        return await self._publish_event(p["channel"], p["message"])

    async def _publish_event(self, channel: str, message: Any) -> int:
        subs = self.subscribers.get(channel, set())
        n = 0
        for conn in list(subs):
            if conn.closed:
                subs.discard(conn)
                continue
            await conn.push("Pub", {"channel": channel, "message": message})
            n += 1
        return n

    # ------------------------------------------------------ placement groups
    async def _create_placement_group(self, conn: Connection, p: Dict) -> Dict:
        """Reserve bundles across nodes with the requested strategy.

        2-phase (prepare on agents, rollback on failure) like the reference's
        PG protocol (reference: node_manager.proto:385-392 Prepare/Commit).
        Infeasible groups stay PENDING and are retried as nodes/resources
        appear (reference: GcsPlacementGroupManager pending queue).
        """
        pg_id = p["pg_id"]
        self.placement_groups[pg_id] = {
            "pg_id": pg_id, "state": "PENDING", "bundles": p["bundles"],
            "strategy": p.get("strategy", "PACK"), "placement": None,
            "name": p.get("name", ""),
        }
        if await self._try_place_pg(pg_id):
            return {"state": "CREATED",
                    "placement": self.placement_groups[pg_id]["placement"]}
        self._hold_task(
            asyncio.get_running_loop().create_task(self._retry_place_pg(pg_id)))
        return {"state": "PENDING"}

    async def _try_place_pg(self, pg_id: str) -> bool:
        pg = self.placement_groups.get(pg_id)
        if pg is None or pg["state"] != "PENDING":
            return pg is not None and pg["state"] == "CREATED"
        bundles = [ResourceSet.from_wire(b) for b in pg["bundles"]]
        placement = self._place_bundles(bundles, pg["strategy"])
        if placement is None:
            return False
        prepared = []
        ok = True
        for idx, (bundle, node_id) in enumerate(zip(bundles, placement)):
            node = self.nodes[node_id]
            try:
                resp = await asyncio.wait_for(
                    self._agent_call(node, "PreparePGBundle",
                                     {"pg_id": pg_id, "bundle_index": idx,
                                      "resources": bundle.to_wire()}),
                    timeout=CONFIG.pg_prepare_timeout_s,
                )
                if resp and resp.get("ok"):
                    prepared.append((node, idx, bundle))
                else:
                    ok = False
                    break
            except Exception:
                # A timed-out prepare may still land on the agent; roll it
                # back too (ReturnPGBundle is idempotent) so the reservation
                # can't leak.
                prepared.append((node, idx, bundle))
                ok = False
                break
        # The group may have been removed while we awaited the prepares;
        # committing would resurrect it and leak the agents' reservations.
        if pg["state"] != "PENDING":
            ok = False
        if not ok:
            for node, idx, bundle in prepared:
                await node.conn.push("ReturnPGBundle",
                                     {"pg_id": pg_id, "bundle_index": idx})
            return False
        pg["state"] = "CREATED"
        self._schedule_save()
        pg["placement"] = placement
        return True

    async def _retry_place_pg(self, pg_id: str) -> None:
        first = True
        while True:
            # fast first retry: a create racing its predecessor's bundle
            # return (concurrent handler dispatch) should land on the
            # next tick, not pay the full retry period
            await asyncio.sleep(0.05 if first
                                else CONFIG.pg_retry_place_period_s)
            first = False
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg["state"] != "PENDING":
                return
            if await self._try_place_pg(pg_id):
                return

    def _place_bundles(self, bundles: List[ResourceSet], strategy: str
                       ) -> Optional[List[str]]:
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return None
        placement: List[str] = []
        # Work on copies of availability so multi-bundle accounting is correct.
        avail = {n.node_id: n.resources.available.copy() for n in alive}
        if strategy in ("STRICT_PACK",):
            for n in alive:
                trial = avail[n.node_id].copy()
                if all(trial.subtract(b) for b in bundles):
                    return [n.node_id] * len(bundles)
            return None
        if strategy in ("STRICT_SPREAD",):
            used = set()
            for b in bundles:
                cand = [n for n in alive
                        if n.node_id not in used and b.fits(avail[n.node_id])]
                if not cand:
                    return None
                cand.sort(key=lambda n: n.resources.utilization())
                placement.append(cand[0].node_id)
                used.add(cand[0].node_id)
                avail[cand[0].node_id].subtract(b)
            return placement
        # PACK / SPREAD: best-effort
        prefer_pack = strategy == "PACK"
        for b in bundles:
            cand = [n for n in alive if b.fits(avail[n.node_id])]
            if not cand:
                return None
            if prefer_pack and placement:
                same = [n for n in cand if n.node_id == placement[-1]]
                if same:
                    cand = same
            elif not prefer_pack:
                cand.sort(key=lambda n: placement.count(n.node_id))
            placement.append(cand[0].node_id)
            avail[cand[0].node_id].subtract(b)
        return placement

    async def _agent_call(self, node: NodeInfo, method: str, payload: Dict):
        """Request/response to an agent over its persistent connection."""
        fut = asyncio.get_running_loop().create_future()
        key = f"__agent_reply__{id(fut)}"
        self.kv.setdefault("__internal__", {})

        # Use an ephemeral reply channel over pubsub semantics: the agent
        # replies by calling "Publish" on channel `key`.
        def cleanup(_):
            self.subscribers.pop(key, None)

        class _FutConn:
            closed = False

            async def push(self_inner, method_inner, p_inner):
                if not fut.done():
                    fut.set_result(p_inner["message"])

        self.subscribers[key] = {_FutConn()}
        fut.add_done_callback(cleanup)
        await node.conn.push(method, {**payload, "reply_channel": key})
        return await fut

    async def _remove_placement_group(self, conn, p) -> Dict:
        pg = self.placement_groups.get(p["pg_id"])
        if not pg:
            return {"ok": False}
        # mark REMOVED before any await: handlers dispatch concurrently,
        # so a Get/Create processed mid-removal must already see the
        # terminal state (and _try_place_pg's state check must abort)
        placement = pg.get("placement")
        pg["state"] = "REMOVED"
        if placement:
            for idx, node_id in enumerate(placement):
                node = self.nodes.get(node_id)
                if node and node.alive:
                    await node.conn.push("ReturnPGBundle",
                                         {"pg_id": p["pg_id"], "bundle_index": idx})
        self._schedule_save()
        return {"ok": True}

    async def _get_placement_group(self, conn, p) -> Optional[Dict]:
        return self.placement_groups.get(p["pg_id"])

    async def _list_placement_groups(self, conn, p) -> List[Dict]:
        return list(self.placement_groups.values())

    # ----------------------------------------------------------- task events
    async def _report_task_events(self, conn, p) -> None:
        # v2: columnar tuples (task_id, job_id, name, state, type, time)
        # with node_id once per frame — dicts are built only on query
        node_id = p.get("node_id", "")
        for ev in p.get("events_v2", ()):
            self.task_events.append((node_id, ev))
        for ev in p.get("events", ()):  # legacy dict form
            self.task_events.append((ev.get("node_id", node_id), ev))
        cap = CONFIG.task_event_buffer_max
        if len(self.task_events) > cap:
            self.task_events = self.task_events[-cap:]

    @staticmethod
    def _event_to_dict(node_id: str, ev) -> Dict:
        if isinstance(ev, dict):
            return ev
        task_id, job_id, name, state, task_type, t = ev
        return {
            "task_id": task_id.hex() if isinstance(task_id, bytes) else task_id,
            "job_id": job_id.hex() if isinstance(job_id, bytes) else job_id,
            "name": name, "state": state, "type": task_type, "time": t,
            "node_id": node_id,
        }

    async def _list_task_events(self, conn, p) -> List[Dict]:
        # filter + slice on the stored tuples, dict-render only the tail —
        # a full buffer is 100k entries and this runs on every poll
        limit = p.get("limit", 1000)
        job = p.get("job_id")
        if job:
            def match(ev):
                if isinstance(ev, dict):
                    return ev.get("job_id") == job
                jid = ev[1]
                return (jid.hex() if isinstance(jid, bytes) else jid) == job

            picked: List = []
            for nid, ev in reversed(self.task_events):
                if match(ev):
                    picked.append((nid, ev))
                    if len(picked) >= limit:
                        break
            picked.reverse()
        else:
            picked = self.task_events[-limit:]
        return [self._event_to_dict(nid, ev) for nid, ev in picked]

    # ----------------------------------------------------------------- jobs
    async def _register_job(self, conn, p) -> None:
        self.jobs[p["job_id"]] = p
        self._schedule_save()

    async def _list_jobs(self, conn, p) -> List[Dict]:
        return list(self.jobs.values())


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--persist", default=os.environ.get(
        "RAY_TPU_GCS_PERSIST", ""))
    args = parser.parse_args()

    async def run():
        import signal

        from ray_tpu._private import lifecycle, proc_profile
        from ray_tpu._private.event import init_event_log, report_event

        from ray_tpu._private.protocol import set_fault_self_id

        set_fault_self_id("head")  # chaos rules may target the head
        lifecycle.register_self("gcs", args.session_dir)
        # die with the spawning driver/runner: a SIGKILL'd driver must not
        # strand the head control plane (lifecycle supervisor contract)
        lifecycle.fate_share_with_parent()
        prof = proc_profile.maybe_start()
        init_event_log(args.session_dir, "head")
        report_event("INFO", "HEAD_STARTED", "head control plane starting")
        head = HeadServer(args.session_dir, args.port,
                          persist_path=args.persist or None)
        port = await head.start()
        # Parent discovers the bound port through this file.
        with open(os.path.join(args.session_dir, "head_port"), "w") as f:
            f.write(str(port))
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        # flush the last debounce window so a clean stop loses nothing
        head._save_state()
        proc_profile.dump(prof, "head")
        lifecycle.unregister_process(args.session_dir, os.getpid())

    asyncio.run(run())


if __name__ == "__main__":
    main()
