"""Workflow depth (VERDICT r3 missing #6 / next #9): continuations +
dynamic step generation, resume-after-kill ACROSS a continuation
boundary, durable HTTP event delivery, and URI-pluggable storage
(reference: python/ray/workflow/workflow_executor.py continuations,
http_event_provider.py, workflow_storage.py)."""

import os
import subprocess
import sys
import uuid

import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def test_continuation_dynamic_steps(cluster, tmp_path):
    """A step decides AT RUNTIME to fan into more steps (recursive
    factorial via continuation — the canonical dynamic-workflow shape)."""
    from ray_tpu import workflow

    workflow.init(str(tmp_path))
    calls = str(tmp_path / "calls")

    @ray_tpu.remote
    def fact(n, acc):
        open(calls, "a").write("x")
        if n <= 1:
            return acc
        return workflow.continuation(fact.bind(n - 1, acc * n))

    out = workflow.run(fact.bind(5, 1), workflow_id="wf_fact")
    assert out == 120
    assert len(open(calls).read()) == 5  # 5 dynamic steps actually ran
    assert workflow.get_status("wf_fact") == "SUCCESSFUL"


def test_resume_after_kill_across_continuation(cluster, tmp_path):
    """Kill the driver MID-CONTINUATION; a fresh process resumes from the
    continuation's own checkpoints: pre-crash steps don't re-run."""
    script = r"""
import os, sys
import ray_tpu
from ray_tpu import workflow

storage, counters, mode = sys.argv[1], sys.argv[2], sys.argv[3]
ray_tpu.init(num_cpus=2)
workflow.init(storage)

@ray_tpu.remote(max_retries=0)
def chain(i, n):
    open(os.path.join(counters, f"ran_{i}"), "a").write("x")
    # crash gate rides the FILESYSTEM, not a captured global: the
    # checkpointed continuation pickles this function by value, so a
    # variable would freeze the crash-run's behavior into the resume
    if i == 2 and os.path.exists(os.path.join(counters, "do_crash")):
        os.unlink(os.path.join(counters, "do_crash"))
        os._exit(7)  # worker dies mid-continuation; no retries -> fail
    if i + 1 >= n:
        return i
    return workflow.continuation(chain.bind(i + 1, n))

out = workflow.run(chain.bind(0, 5), workflow_id="wf_kill")
print("RESULT", out)
ray_tpu.shutdown()
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    counters = tmp_path / "counters"
    counters.mkdir()
    (counters / "do_crash").touch()
    storage = str(tmp_path / "wf_storage")

    first = subprocess.run(
        [sys.executable, "-c", script, storage, str(counters), "crash"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert first.returncode != 0, first.stdout + first.stderr
    # steps 0 and 1 committed before the crash (2 started but died)
    assert (counters / "ran_0").exists() and (counters / "ran_1").exists()
    assert not (counters / "ran_3").exists()

    second = subprocess.run(
        [sys.executable, "-c", script, storage, str(counters), "resume"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "RESULT 4" in second.stdout
    # steps 0 and 1 were served from checkpoints — ran exactly once ever
    assert open(counters / "ran_0").read() == "x"
    assert open(counters / "ran_1").read() == "x"
    # step 2 ran in the crashed attempt AND the resume; 3,4 resume-only
    assert open(counters / "ran_2").read() == "xx"
    assert open(counters / "ran_4").read() == "x"


def test_http_event_durable_delivery(cluster, tmp_path):
    """The HTTP provider commits the payload to storage BEFORE acking;
    a workflow that starts after delivery still sees the event."""
    from ray_tpu import workflow

    workflow.init(str(tmp_path))
    key = f"evt-{uuid.uuid4().hex[:6]}"

    # deliver first, in a plain thread (sender side)
    import threading
    import time
    import urllib.request

    listener = workflow.HTTPEventProvider(key, timeout_s=60)

    def send():
        port_rel = f"_events/{key}.port"
        store = workflow._Store(workflow._storage_root)
        for _ in range(200):
            data = store.read_bytes(port_rel)
            if data:
                port = int(data)
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/event/{key}",
                    data=b"payload-42", method="POST")
                urllib.request.urlopen(req, timeout=10)
                return
            time.sleep(0.05)

    t = threading.Thread(target=send, daemon=True)
    t.start()
    payload = listener.poll_for_event()
    t.join(timeout=30)
    assert payload == b"payload-42"
    # durable: a SECOND poll (fresh listener — the resume path) returns the
    # committed payload without any HTTP server
    again = workflow.HTTPEventProvider(key, timeout_s=1).poll_for_event()
    assert again == b"payload-42"


def test_workflow_remote_storage(cluster):
    """Checkpoints land in a (fake) bucket via the storage registry —
    completed steps survive with no local dir at all."""
    from ray_tpu import workflow
    from ray_tpu._private.storage import get_storage_backend

    bucket = f"mock://wfbucket-{uuid.uuid4().hex[:8]}"
    try:
        workflow.init(bucket)

        @ray_tpu.remote
        def double(x):
            return x * 2

        out = workflow.run(double.bind(21), workflow_id="wf_remote")
        assert out == 42
        assert workflow.get_status("wf_remote") == "SUCCESSFUL"
        backend = get_storage_backend(bucket)
        assert backend.exists(bucket + "/wf_remote/status.json")
        # resume is served entirely from the bucket
        assert workflow.resume("wf_remote", double.bind(21)) == 42
    finally:
        get_storage_backend(bucket).delete(bucket)
        workflow.init(os.path.expanduser("~/ray_tpu_workflows"))


def test_virtual_actor_durable_state(cluster, tmp_path):
    """Durable actor: state survives a fresh handle (new 'process'), every
    method call is a real task, and commits are atomic."""
    from ray_tpu import workflow

    workflow.init(str(tmp_path))

    @workflow.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

        def get(self):
            return self.n

    c = Counter.get_or_create("c1", 10)
    assert c.incr.run() == 11
    assert c.incr.run(5) == 16
    # a FRESH handle (e.g. a new driver after a crash) sees committed state
    c2 = Counter.get_or_create("c1")
    assert c2.get.run() == 16
    assert c2.state()["n"] == 16
    # run_async
    assert c2.incr.run_async(4).result(timeout=60) == 20
    # an unrelated actor id starts from its own init args
    other = Counter.get_or_create("c2", 100)
    assert other.get.run() == 100
