from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.bayesopt import BayesOptSearcher
from ray_tpu.tune.search.hyperopt import HyperOptSearch
from ray_tpu.tune.search.optuna import OptunaSearch
from ray_tpu.tune.search.searcher import ConcurrencyLimiter, Searcher
from ray_tpu.tune.search.tpe import TPESearcher, TuneBOHB

__all__ = ["Searcher", "ConcurrencyLimiter", "BasicVariantGenerator",
           "OptunaSearch", "HyperOptSearch", "TPESearcher", "TuneBOHB",
           "BayesOptSearcher"]
