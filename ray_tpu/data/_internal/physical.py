"""Physical operators for the streaming executor.

Reference: python/ray/data/_internal/execution/operators/ —
``TaskPoolMapOperator``, ``ActorPoolMapOperator``, ``InputDataBuffer``,
limit/union/zip, and the all-to-all planner (_internal/planner/). Blocks flow
as ``RefBundle``s (block ObjectRef + metadata); payloads stay in the object
store and only metadata crosses the executor.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data._internal.logical import MapSpec


class RefBundle:
    __slots__ = ("block_ref", "meta")

    def __init__(self, block_ref, meta: BlockMetadata):
        self.block_ref = block_ref
        self.meta = meta


# --------------------------------------------------------------------- UDFs
def _apply_specs(specs: List[MapSpec], block: Block) -> Block:
    """Run a fused chain of transforms over one block inside a task.

    Zero-copy fusion (reference: _internal/logical/rules/
    zero_copy_map_fusion.py): a RUN of consecutive whole-block "batches"
    transforms with the same batch_format passes each UDF's output batch
    STRAIGHT into the next UDF — no block materialization + re-extraction
    round-trip between fused stages."""
    acc = BlockAccessor(block)
    i = 0
    while i < len(specs):
        spec = specs[i]
        fn = spec.fn
        kwargs = spec.fn_kwargs or {}
        if spec.kind == "batches":
            bs = spec.batch_size
            n = acc.num_rows()
            if bs is None or n <= bs:
                fmt = spec.batch_format
                out = acc.to_batch(fmt)
                # drain the whole same-format whole-block run zero-copy;
                # n is re-derived after every UDF — an expanding UDF must
                # not smuggle an oversized batch past a downstream
                # batch_size (fixed-shape jitted fns depend on it)
                while i < len(specs) and specs[i].kind == "batches" \
                        and specs[i].batch_format == fmt \
                        and (specs[i].batch_size is None
                             or n <= specs[i].batch_size):
                    s = specs[i]
                    out = s.fn(out, *s.fn_args, **(s.fn_kwargs or {}))
                    i += 1
                    try:
                        n = len(next(iter(out.values())))
                    except Exception:
                        break  # unknown shape: fall back to block path
                block = BlockAccessor.batch_to_block(out)
                acc = BlockAccessor(block)
                continue
            else:
                # honor batch_size by re-chunking the block — critical for
                # fixed-shape jitted UDFs (reference: block_batching/)
                outs = []
                for s in range(0, n, bs):
                    chunk = BlockAccessor(acc.slice(s, min(s + bs, n)))
                    out = fn(chunk.to_batch(spec.batch_format),
                             *spec.fn_args, **kwargs)
                    outs.append(BlockAccessor.batch_to_block(out))
                block = BlockAccessor.concat(outs)
        elif spec.kind == "rows":
            rows = [fn(r, *spec.fn_args, **kwargs) for r in acc.iter_rows()]
            block = BlockAccessor.rows_to_block(rows)
        elif spec.kind == "flat":
            rows = []
            for r in acc.iter_rows():
                rows.extend(fn(r, *spec.fn_args, **kwargs))
            block = BlockAccessor.rows_to_block(rows)
        elif spec.kind == "block":
            # whole-block transform (zero-copy Arrow ops: select/drop/rename)
            block = fn(block, *spec.fn_args, **kwargs)
        elif spec.kind == "filter":
            keep = np.asarray(
                [bool(fn(r, *spec.fn_args, **kwargs))
                 for r in acc.iter_rows()])
            idx = np.nonzero(keep)[0]
            block = acc.take_indices(idx)
        else:
            raise ValueError(f"unknown map kind {spec.kind!r}")
        acc = BlockAccessor(block)
        i += 1
    return block


def _map_task(specs: List[MapSpec], block: Block):
    t0 = time.perf_counter()
    out = _apply_specs(specs, block)
    meta = BlockAccessor(out).metadata(exec_time_s=time.perf_counter() - t0)
    return out, meta


def _read_task(read_fn: Callable[[], Any], specs: List[MapSpec]):
    """Run a datasource read and any fused transforms; one output block."""
    t0 = time.perf_counter()
    out = read_fn()
    blocks = list(out) if isinstance(out, (list, tuple)) else [out]
    blocks = [BlockAccessor.batch_to_block(b) if isinstance(b, (dict, list))
              else b for b in blocks]
    block = BlockAccessor.concat(blocks) if len(blocks) != 1 else blocks[0]
    if specs:
        block = _apply_specs(specs, block)
    meta = BlockAccessor(block).metadata(exec_time_s=time.perf_counter() - t0)
    return block, meta


class _MapWorker:
    """Actor for class-based UDFs (reference: ActorPoolMapOperator's
    _MapWorker). The UDF class is constructed once per actor; batches stream
    through it — the pattern for carrying an expensive jitted model."""

    def __init__(self, fn_cls_blob: bytes, args: tuple, kwargs: dict):
        import cloudpickle

        cls = cloudpickle.loads(fn_cls_blob)
        self._udf = cls(*args, **(kwargs or {}))

    def map(self, specs: List[MapSpec], block: Block):
        specs = [MapSpec(**{**s.__dict__, "fn": self._udf})
                 if s.fn is None else s for s in specs]
        return _map_task(specs, block)

    def ready(self):
        return True


# ---------------------------------------------------------------- operators
class PhysicalOperator:
    """Base: pull bundles from ``input_queue``, expose them on
    ``output_queue``. The executor wires queues and drives ``poll``/
    ``dispatch``."""

    def __init__(self, name: str):
        self.name = name
        self.input_queue: collections.deque = collections.deque()
        self.output_queue: collections.deque = collections.deque()
        self.inputs_complete = False
        # per-operator accounting surfaced by Dataset.stats() (reference:
        # python/ray/data/_internal/stats.py OpRuntimeMetrics)
        self.rows_in = 0
        self.bytes_in = 0
        self.rows_out = 0
        self.bytes_out = 0
        self.blocks_out = 0
        self.exec_time_s = 0.0
        self.tasks_launched = 0
        self.first_activity_t: float = 0.0
        self.last_activity_t: float = 0.0

    # --- scheduling interface
    def num_active_tasks(self) -> int:
        return 0

    def can_dispatch(self) -> bool:
        return bool(self.input_queue)

    def dispatch(self) -> None:
        raise NotImplementedError

    def poll(self) -> None:
        pass

    def all_inputs_done(self) -> None:
        self.inputs_complete = True

    def completed(self) -> bool:
        return (self.inputs_complete and not self.input_queue
                and self.num_active_tasks() == 0)

    def extra_usage_bytes(self) -> int:
        """Bytes this operator holds OUTSIDE its input/output queues
        (e.g. the shuffle's sealed shards); counted by the
        ResourceManager toward the global memory budget."""
        return 0

    def stats_extras(self) -> Dict:
        """Operator-specific counters merged into the stats record."""
        return {}

    def _emit(self, bundle: RefBundle) -> None:
        import time as _t

        now = _t.perf_counter()
        if not self.first_activity_t:
            self.first_activity_t = now
        self.last_activity_t = now
        self.rows_out += bundle.meta.num_rows
        self.bytes_out += bundle.meta.size_bytes or 0
        self.blocks_out += 1
        self.exec_time_s += bundle.meta.exec_time_s
        self.output_queue.append(bundle)

    def _note_input(self, bundle: RefBundle) -> None:
        import time as _t

        if not self.first_activity_t:
            self.first_activity_t = _t.perf_counter()
        self.rows_in += bundle.meta.num_rows
        self.bytes_in += bundle.meta.size_bytes or 0


class InputDataBuffer(PhysicalOperator):
    def __init__(self, bundles: List[RefBundle]):
        super().__init__("Input")
        self.output_queue.extend(bundles)
        self.inputs_complete = True

    def can_dispatch(self) -> bool:
        return False

    def completed(self) -> bool:
        return True


class TaskPoolMapOperator(PhysicalOperator):
    """Map via stateless tasks; also hosts fused Read stages
    (reference: operators/task_pool_map_operator.py)."""

    def __init__(self, name: str, specs: List[MapSpec],
                 read_tasks: Optional[List[Callable]] = None,
                 max_concurrency: Optional[int] = None,
                 ray_remote_args: Optional[Dict] = None):
        super().__init__(name)
        self.specs = specs
        if max_concurrency is None:
            from ray_tpu.data.context import DataContext

            max_concurrency = \
                DataContext.get_current().max_tasks_in_flight_per_op
        self.max_concurrency = max_concurrency
        self.ray_remote_args = dict(ray_remote_args or {})
        self._inflight: List[Tuple[Any, Any]] = []  # (block_ref, meta_ref)
        if read_tasks is not None:
            self.input_queue.extend(read_tasks)
            self.inputs_complete = True
        self._is_read = read_tasks is not None

    def num_active_tasks(self) -> int:
        return len(self._inflight)

    def can_dispatch(self) -> bool:
        # the concurrency cap lives in ConcurrencyCapBackpressurePolicy
        # (data/_internal/backpressure.py) — ONE source of truth, so
        # replacing the policy chain actually changes the rule
        return bool(self.input_queue)

    def dispatch(self) -> None:
        item = self.input_queue.popleft()
        opts = {"num_returns": 2, "name": f"Data::{self.name}",
                **self.ray_remote_args}
        if self._is_read:
            refs = ray_tpu.remote(_read_task).options(**opts).remote(
                item, self.specs)
        else:
            refs = ray_tpu.remote(_map_task).options(**opts).remote(
                self.specs, item.block_ref)
        self.tasks_launched += 1
        self._inflight.append((refs[0], refs[1]))

    def poll(self) -> None:
        # Emit strictly in dispatch order so downstream zip/take see blocks
        # in input order (reference: execution_options.preserve_order).
        while self._inflight:
            block_ref, meta_ref = self._inflight[0]
            ready, _ = ray_tpu.wait([meta_ref], num_returns=1, timeout=0)
            if not ready:
                return
            self._inflight.pop(0)
            meta = ray_tpu.get(meta_ref)  # raises on task error
            self._emit(RefBundle(block_ref, meta))


class ActorPoolMapOperator(PhysicalOperator):
    """Map via a fixed pool of UDF actors
    (reference: operators/actor_pool_map_operator.py)."""

    MAX_TASKS_PER_ACTOR = 2

    def __init__(self, name: str, specs: List[MapSpec], fn_cls,
                 pool_size: int = 2,
                 fn_constructor_args: tuple = (),
                 fn_constructor_kwargs: Optional[dict] = None,
                 ray_remote_args: Optional[Dict] = None):
        super().__init__(name)
        import cloudpickle

        self.specs = [MapSpec(**{**s.__dict__, "fn": None}) for s in specs]
        self._actors = []
        self._load: Dict[int, int] = {}
        blob = cloudpickle.dumps(fn_cls)
        opts = dict(ray_remote_args or {})
        actor_cls = ray_tpu.remote(_MapWorker)
        for i in range(pool_size):
            a = (actor_cls.options(**opts) if opts else actor_cls).remote(
                blob, fn_constructor_args, fn_constructor_kwargs or {})
            self._actors.append(a)
            self._load[i] = 0
        self._inflight: List[Tuple[int, Any, Any]] = []

    def num_active_tasks(self) -> int:
        return len(self._inflight)

    def can_dispatch(self) -> bool:
        return (bool(self.input_queue)
                and any(v < self.MAX_TASKS_PER_ACTOR
                        for v in self._load.values()))

    def dispatch(self) -> None:
        idx = min(self._load, key=self._load.get)
        bundle = self.input_queue.popleft()
        refs = self._actors[idx].map.options(num_returns=2).remote(
            self.specs, bundle.block_ref)
        self.tasks_launched += 1
        self._load[idx] += 1
        self._inflight.append((idx, refs[0], refs[1]))

    def poll(self) -> None:
        while self._inflight:
            idx, block_ref, meta_ref = self._inflight[0]
            ready, _ = ray_tpu.wait([meta_ref], num_returns=1, timeout=0)
            if not ready:
                return
            self._inflight.pop(0)
            meta = ray_tpu.get(meta_ref)
            self._load[idx] -= 1
            self._emit(RefBundle(block_ref, meta))

    def shutdown(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class LimitOperator(PhysicalOperator):
    def __init__(self, limit: int):
        super().__init__(f"Limit[{limit}]")
        self.limit = limit
        self._taken = 0
        # the one boundary-slice task in flight: (block_ref, meta_ref).
        # Its metadata resolves in poll() — the old synchronous
        # ray_tpu.get here stalled the whole scheduling loop for a full
        # task round trip (ISSUE 12 hygiene).
        self._slice_inflight: Optional[Tuple[Any, Any]] = None

    def num_active_tasks(self) -> int:
        return 1 if self._slice_inflight else 0

    def can_dispatch(self) -> bool:
        return (bool(self.input_queue) and self._taken < self.limit
                and self._slice_inflight is None)

    def dispatch(self) -> None:
        bundle = self.input_queue.popleft()
        remaining = self.limit - self._taken
        if bundle.meta.num_rows <= remaining:
            self._taken += bundle.meta.num_rows
            self._emit(bundle)
        else:
            refs = ray_tpu.remote(_slice_task).options(num_returns=2).remote(
                bundle.block_ref, 0, remaining)
            self.tasks_launched += 1
            # the slice is exactly `remaining` rows (the bundle had
            # more): account now so the limit closes without waiting
            self._taken += remaining
            self._slice_inflight = (refs[0], refs[1])

    def poll(self) -> None:
        if self._slice_inflight is not None:
            block_ref, meta_ref = self._slice_inflight
            ready, _ = ray_tpu.wait([meta_ref], num_returns=1, timeout=0)
            if ready:
                self._slice_inflight = None
                self._emit(RefBundle(block_ref, ray_tpu.get(meta_ref)))
        if self._taken >= self.limit:
            self.input_queue.clear()
            self.inputs_complete = True

    def completed(self) -> bool:
        if self._slice_inflight is not None:
            return False
        return self._taken >= self.limit or super().completed()


def _slice_task(block: Block, start: int, end: int):
    out = BlockAccessor(block).slice(start, end)
    return out, BlockAccessor(out).metadata()


class AllToAllOperator(PhysicalOperator):
    """Barrier operator: buffers every input bundle, then runs ``bulk_fn``
    once (reference: planner/exchange/ shuffle task scheme)."""

    def __init__(self, name: str,
                 bulk_fn: Callable[[List[RefBundle]], List[RefBundle]]):
        super().__init__(name)
        self.bulk_fn = bulk_fn
        self._ran = False

    def can_dispatch(self) -> bool:
        return self.inputs_complete and not self._ran

    def dispatch(self) -> None:
        bundles = list(self.input_queue)
        self.input_queue.clear()
        t0 = time.perf_counter()
        for out in self.bulk_fn(bundles):
            self._emit(out)
        self.exec_time_s += time.perf_counter() - t0
        self._ran = True

    def completed(self) -> bool:
        return self._ran


class UnionOperator(PhysicalOperator):
    """Pass-through merge of several upstream branches; the executor wires
    every branch's output here."""

    def __init__(self, n_branches: int):
        super().__init__("Union")
        self._branches_done = 0
        self.n_branches = n_branches

    def can_dispatch(self) -> bool:
        return bool(self.input_queue)

    def dispatch(self) -> None:
        self._emit(self.input_queue.popleft())

    def branch_done(self) -> None:
        self._branches_done += 1
        if self._branches_done >= self.n_branches:
            self.inputs_complete = True


class ZipOperator(PhysicalOperator):
    """Barrier zip of two branches by row position."""

    def __init__(self):
        super().__init__("Zip")
        self.left: List[RefBundle] = []
        self.right: List[RefBundle] = []
        self._left_done = False
        self._right_done = False
        self._ran = False
        self._inflight: Optional[Tuple[Any, Any]] = None

    def add_left(self, b: RefBundle):
        self.left.append(b)

    def add_right(self, b: RefBundle):
        self.right.append(b)

    def can_dispatch(self) -> bool:
        return self._left_done and self._right_done and not self._ran

    def dispatch(self) -> None:
        lrefs = [b.block_ref for b in self.left]
        rrefs = [b.block_ref for b in self.right]
        refs = ray_tpu.remote(_zip_task).options(num_returns=2).remote(
            lrefs, rrefs)
        self.tasks_launched += 1
        self._inflight = (refs[0], refs[1])
        self._ran = True

    def num_active_tasks(self) -> int:
        return 1 if getattr(self, "_inflight", None) else 0

    def poll(self) -> None:
        # resolve the zip's metadata here instead of blocking dispatch
        # (the scheduling loop kept running other operators meanwhile)
        inflight = getattr(self, "_inflight", None)
        if inflight is None:
            return
        block_ref, meta_ref = inflight
        ready, _ = ray_tpu.wait([meta_ref], num_returns=1, timeout=0)
        if ready:
            self._inflight = None
            self._emit(RefBundle(block_ref, ray_tpu.get(meta_ref)))

    def completed(self) -> bool:
        return self._ran and getattr(self, "_inflight", None) is None


def _zip_task(left_refs, right_refs):
    lblocks = [ray_tpu.get(r) for r in left_refs]
    rblocks = [ray_tpu.get(r) for r in right_refs]
    lb = BlockAccessor.concat(lblocks)
    rb = BlockAccessor.concat(rblocks)
    la, ra = BlockAccessor(lb), BlockAccessor(rb)
    if la.num_rows() != ra.num_rows():
        raise ValueError(
            f"zip: datasets have different row counts "
            f"({la.num_rows()} vs {ra.num_rows()})")
    ld, rd = la.to_numpy_dict(), ra.to_numpy_dict()
    for k, v in rd.items():
        name = k
        while name in ld:
            name = name + "_1"
        ld[name] = v
    out = BlockAccessor.batch_to_block(ld)
    return out, BlockAccessor(out).metadata()
