"""Task specifications — the unit handed from submitter to executor.

Parity with the reference's ``TaskSpecification`` (reference:
``src/ray/common/task/task_spec.h``): function identity, serialized args with
by-value / by-reference entries, return count, resource request, retry policy,
actor linkage and scheduling strategy — all in one msgpack-able record that
crosses the wire as-is.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple


def runtime_env_key(runtime_env: Optional[Dict]) -> Optional[str]:
    """Canonical hashable form of a runtime_env — THE key for both lease
    scheduling (below) and agent-side worker/env affinity
    (agent._pop_idle_worker); keep the two in sync by using only this."""
    if not runtime_env:
        return None
    return json.dumps(runtime_env, sort_keys=True)

NORMAL_TASK = 0
ACTOR_CREATION_TASK = 1
ACTOR_TASK = 2

# Argument entries on the wire:
#   ("v", bytes)                       — serialized value (may embed nested refs)
#   ("r", object_id_bytes, owner_addr) — pass-by-reference, fetch before run


_WIRE_FIELDS = (
    "task_id", "job_id", "task_type", "function_id", "function_blob",
    "function_name", "args", "kwargs", "num_returns", "resources",
    "max_retries", "retry_exceptions", "owner_addr", "actor_id",
    "actor_method", "seq", "scheduling_strategy", "placement_group_id",
    "placement_group_bundle_index", "max_concurrency", "namespace",
    "actor_name", "max_restarts", "runtime_env", "label_selector",
    # flight-recorder trace context (ISSUE 14): (trace_id, parent_span_id)
    # or None. Riding the spec wire is what propagates a sampled trace
    # across every transport for free — TCP, mux streams, and the shm
    # lane all carry the same per-call dict.
    "trace_ctx",
    # lineage reconstruction (ISSUE 17): deterministic RNG seed stamped
    # at first submission and replayed verbatim, so a reconstructed
    # return is byte-identical to the original even when the task body
    # draws randomness. None = task never seeded (pre-17 senders).
    "replay_seed",
)


_WIRE_FIELD_SET = frozenset(_WIRE_FIELDS)

# non-None __init__ defaults, used when a wire dict omits a field
_WIRE_DEFAULTS = {
    "max_retries": 0, "retry_exceptions": False, "actor_method": "",
    "seq": 0, "placement_group_bundle_index": -1, "max_concurrency": 1,
    "namespace": "", "actor_name": "", "max_restarts": 0,
}


class TaskSpec:
    __slots__ = _WIRE_FIELDS + ("_wire",)

    def __init__(
        self,
        task_id: bytes,
        job_id: bytes,
        task_type: int,
        function_id: bytes,
        function_name: str,
        args: List[Tuple],
        kwargs: Dict[str, Tuple],
        num_returns: int,
        resources: Dict[str, int],
        owner_addr: Dict[str, Any],
        function_blob: Optional[bytes] = None,
        max_retries: int = 0,
        retry_exceptions: bool = False,
        actor_id: Optional[bytes] = None,
        actor_method: str = "",
        seq: int = 0,
        scheduling_strategy: Optional[Any] = None,
        placement_group_id: Optional[bytes] = None,
        placement_group_bundle_index: int = -1,
        max_concurrency: int = 1,
        namespace: str = "",
        actor_name: str = "",
        max_restarts: int = 0,
        runtime_env: Optional[Dict] = None,
        label_selector: Optional[Dict[str, str]] = None,
        trace_ctx: Optional[Tuple[int, int]] = None,
        replay_seed: Optional[int] = None,
    ):
        self.task_id = task_id
        self.job_id = job_id
        self.task_type = task_type
        self.function_id = function_id
        self.function_blob = function_blob
        self.function_name = function_name
        self.args = args
        self.kwargs = kwargs
        self.num_returns = num_returns
        self.resources = resources
        self.max_retries = max_retries
        self.retry_exceptions = retry_exceptions
        self.owner_addr = owner_addr
        self.actor_id = actor_id
        self.actor_method = actor_method
        self.seq = seq
        self.scheduling_strategy = scheduling_strategy
        self.placement_group_id = placement_group_id
        self.placement_group_bundle_index = placement_group_bundle_index
        self.max_concurrency = max_concurrency
        self.namespace = namespace
        self.actor_name = actor_name
        self.max_restarts = max_restarts
        self.runtime_env = runtime_env
        self.label_selector = label_selector
        self.trace_ctx = trace_ctx
        self.replay_seed = replay_seed
        self._wire = None

    def to_wire(self) -> Dict[str, Any]:
        # specs are immutable after construction; dispatch sits on the
        # task/actor-call hot path, so the wire dict is built once. Callers
        # that add per-dispatch keys (assigned_instances) copy first.
        w = self._wire
        if w is None:
            self._wire = w = {s: getattr(self, s) for s in _WIRE_FIELDS}
        return w

    def __getattr__(self, name):
        # Lazy wire-backed spec (ISSUE 18): SpecTemplate.instantiate sets
        # ONLY ``_wire`` — each slot fills on first read from the wire
        # dict, so the submit hot loop pays a dict copy instead of 26
        # eager setattrs per task. Fully-initialized specs never enter
        # here (__getattr__ fires only on unset slots).
        try:
            wire = object.__getattribute__(self, "_wire")
        except AttributeError:
            raise AttributeError(name) from None
        if wire is not None and name in _WIRE_FIELD_SET:
            val = wire.get(name, _WIRE_DEFAULTS.get(name))
            setattr(self, name, val)
            return val
        raise AttributeError(name)

    @classmethod
    def from_wire(cls, wire: Dict[str, Any]) -> "TaskSpec":
        # executor-side hot path: fill slots directly, tolerating extra
        # keys (assigned_instances rides the same frame) and missing ones
        # (older senders) without a 26-kwarg call
        self = cls.__new__(cls)
        get = wire.get
        for s in _WIRE_FIELDS:
            setattr(self, s, get(s, _WIRE_DEFAULTS.get(s)))
        self._wire = None
        return self

    def scheduling_key(self) -> Tuple:
        """Tasks with the same key can reuse the same leased worker
        (reference: direct_task_transport.h SchedulingKey)."""
        return (
            tuple(sorted(self.resources.items())),
            self.placement_group_id,
            repr(self.scheduling_strategy),
            runtime_env_key(self.runtime_env),
            # retriability rides the key so the OOM killing policy can
            # prefer killing leases whose tasks will be retried
            self.max_retries > 0,
        )


class SpecTemplate:
    """Frozen submission template for one (function, options) signature
    (ISSUE 18). Everything invariant across repeated calls of the same
    signature — function identity, resources, retry policy, scheduling
    strategy, owner address — is resolved ONCE into a base wire dict;
    per-call work reduces to splicing the task id, args and trace fields
    into a copy. Keyed by function id + options hash in the worker's
    template cache: a redefined function hashes to a new function id, so
    stale templates can never serve the new body.
    """

    __slots__ = ("base", "sched_key", "has_ref_args")

    def __init__(self, **invariant):
        base = {s: None for s in _WIRE_FIELDS}
        base.update(_WIRE_DEFAULTS)
        base.update(invariant)
        self.base = base
        # scheduling_key is invariant too: compute it once here instead of
        # per spec (it feeds the lease-pool lookup on every submit)
        probe = TaskSpec.from_wire(base)
        self.sched_key = probe.scheduling_key()

    def instantiate(self, task_id: bytes, args: List[Tuple],
                    kwargs: Dict[str, Tuple],
                    trace_ctx: Optional[Tuple] = None,
                    replay_seed: Optional[int] = None,
                    seq: int = 0) -> TaskSpec:
        """Splice the per-call fields into a copy of the base wire dict
        and hang it straight on the spec: ``to_wire()`` never rebuilds
        what the template already resolved, and the spec's slots stay
        EMPTY until first read (TaskSpec.__getattr__ fills them lazily
        from the wire), so per-task spec cost is one dict copy."""
        w = self.base.copy()
        w["task_id"] = task_id
        w["args"] = args
        w["kwargs"] = kwargs
        w["trace_ctx"] = trace_ctx
        w["replay_seed"] = replay_seed
        if seq:
            w["seq"] = seq
        spec = TaskSpec.__new__(TaskSpec)
        spec._wire = w
        return spec
