"""Shared AIR-style configs (reference: python/ray/air/config.py —
ScalingConfig :101, FailureConfig :377, CheckpointConfig :427,
RunConfig :576), re-based on TPU topology.

``ScalingConfig`` speaks TPU natively: a worker is one *host* of a pod
slice; ``topology`` names the slice type whose chips-per-host product sets
the per-worker accelerator count. ``mesh_shape`` carries the (dp, fsdp, seq,
tensor) axes the JaxTrainer hands to ``ray_tpu.parallel.mesh``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    # resources per training worker actor (e.g. {"TPU": 4}); CPU default 1.
    resources_per_worker: Optional[Dict[str, float]] = None
    # named TPU slice topology, e.g. "v5e-8" (reference models slices via
    # custom resources, tpu.py:335-398); informational + used for defaults.
    topology: Optional[str] = None
    # mesh axes for in-worker SPMD: {"data": -1, "fsdp": 1, ...}
    mesh_shape: Optional[Dict[str, int]] = None
    placement_strategy: str = "PACK"
    # Elastic bounds. When min_workers is set (< num_workers), a worker
    # death during training shrinks the group to the surviving world size
    # (floored at min_workers) instead of restarting at full strength —
    # the preemption-survival mode for slices that can re-shard.
    # max_workers caps future re-grows (defaults to num_workers).
    min_workers: Optional[int] = None
    max_workers: Optional[int] = None

    def __post_init__(self):
        if self.min_workers is not None and not (
                1 <= self.min_workers <= self.num_workers):
            raise ValueError(
                f"min_workers={self.min_workers} must be in "
                f"[1, num_workers={self.num_workers}]")
        if self.max_workers is not None and self.max_workers < self.num_workers:
            raise ValueError(
                f"max_workers={self.max_workers} must be >= "
                f"num_workers={self.num_workers}")

    @property
    def elastic(self) -> bool:
        return (self.min_workers is not None
                and self.min_workers < self.num_workers)

    def _resources(self) -> Dict[str, float]:
        if self.resources_per_worker:
            return dict(self.resources_per_worker)
        if self.use_tpu:
            return {"TPU": float(self.chips_per_worker), "CPU": 1.0}
        return {"CPU": 1.0}

    @property
    def chips_per_worker(self) -> int:
        if self.resources_per_worker and "TPU" in self.resources_per_worker:
            return int(self.resources_per_worker["TPU"])
        if not self.use_tpu:
            return 0
        if self.topology:
            from ray_tpu._private.accelerators.tpu import (
                TPUAcceleratorManager)

            chips = TPUAcceleratorManager.chips_per_host_for_topology(
                self.topology)
            if chips:
                return chips
        return 4

    def as_placement_group_bundles(self) -> list:
        return [self._resources() for _ in range(self.num_workers)]


@dataclasses.dataclass
class FailureConfig:
    """Worker-group restart policy. On TPU the failure domain is the slice:
    one dead host invalidates the whole mesh, so recovery always restarts
    the full worker group (SURVEY §2.5 elastic row)."""

    max_failures: int = 0
    fail_fast: bool = False


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    # dict {metric: threshold}, callable (trial_id, result) -> bool, or a
    # ray_tpu.tune.stopper.Stopper
    stop: Optional[Any] = None
    # list of ray_tpu.tune.logger.Callback (loggers are added by default)
    callbacks: Optional[list] = None
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.expanduser("~/ray_tpu_results")
