"""Control-plane RPC fabric: length-prefixed msgpack over unix/TCP sockets.

This is the role gRPC plays in the reference (reference: ``src/ray/rpc/``
GrpcServer/GrpcClient and the 22 .proto contracts) — here the wire format is
msgpack frames and the server side is a single asyncio event loop per process,
matching the reference's single-threaded asio io_context discipline
(reference: ``src/ray/common/asio/instrumented_io_context.h``).

Frame:    <u32 little-endian length><msgpack payload>
Request:  {"m": method, "i": req_id, "p": payload}
Reply:    {"r": req_id, "p": payload}  or  {"r": req_id, "e": [type, msg]}
Push:     {"m": method, "i": 0, "p": payload}     (one-way, no reply)
"""

from __future__ import annotations

import asyncio
import os
import random
import socket
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import msgpack

from .async_util import hold_task

_HDR = struct.Struct("<I")
MAX_FRAME = 1 << 31
# Raw (bulk) payloads are written in slices with a drain between them:
# the selector transport consumes its buffer with `del buf[:sent]`, so one
# 5 MB write pays ~20 memmoves of the multi-MB remainder (O(n^2) per
# chunk, measured 2.5x throughput loss); sliced writes keep the buffer
# near the water marks instead.
RAW_WRITE_SLICE = 512 * 1024
# Transport write high/low water marks for connections that move bulk
# data (default 64 KB pauses/resumes the writer every few packets).
RAW_WATER_HIGH = 1 << 20
RAW_WATER_LOW = 256 * 1024
# StreamReader buffer limit for data-channel clients (default 64 KB makes
# a 5 MB raw body arrive in ~80 reader wakeups).
DATA_CHANNEL_READER_LIMIT = 4 << 20

# Per-process RPC fabric counters (reference: src/ray/stats grpc_server_*
# / grpc_client_* series). Plain ints bumped on the hot path; the node
# agent and head read them into callback gauges each metrics period.
STATS = {"frames_in": 0, "bytes_in": 0, "frames_out": 0, "bytes_out": 0}


def pack(msg: Any) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _HDR.pack(len(body)) + body


# ---------------------------------------------------------------------------
# Fault injection (partition simulation)
# ---------------------------------------------------------------------------
#
# Every transport in this module consults a process-local FaultSchedule
# before sending and after receiving a frame. A matching rule can DROP the
# frame (silently — the socket stays open, no RST, exactly what a network
# partition or a gray failure looks like to the peer) or DELAY it. Rules
# match on:
#   self:      node_id of THIS process ("*" = any). Agents/workers carry
#              RAY_TPU_NODE_ID; the head tags itself "head".
#   peer:      "tcp" (cross-host traffic only — unix sockets to the local
#              agent are spared, so a "partition" cuts the network, not
#              the host), "unix", or "*".
#   direction: "in" | "out" | "both".
#   method:    frame method name, or "*" (replies match only "*" — a
#              blackhole rule covers them).
#   action:    "drop" | "blackhole" (alias of drop) | "delay" (delay_s).
#
# Two control planes share the same schedule object:
#   - in-process: set_fault_schedule(FaultSchedule(...)) — unit tests.
#   - cross-process: RAY_TPU_FAULT_INJECTION=1 + a JSON rule file at
#     $RAY_TPU_FAULT_FILE (default <session_dir>/fault_schedule.json),
#     polled with a short TTL so util/chaos.NetworkPartitioner can flip
#     partitions on live daemons it never execs into. The TTL check is a
#     single monotonic() compare per frame; with injection disabled the
#     whole feature costs one global load + `is None`.


class FaultRule:
    __slots__ = ("self_id", "peer", "direction", "method", "action",
                 "delay_s")

    def __init__(self, self_id: str = "*", peer: str = "tcp",
                 direction: str = "both", method: str = "*",
                 action: str = "drop", delay_s: float = 0.0):
        self.self_id = self_id
        self.peer = peer
        self.direction = direction
        self.method = method
        self.action = "drop" if action == "blackhole" else action
        self.delay_s = float(delay_s)

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultRule":
        return cls(d.get("self", "*"), d.get("peer", "tcp"),
                   d.get("direction", "both"), d.get("method", "*"),
                   d.get("action", "drop"), d.get("delay_s", 0.0))


class FaultSchedule:
    """An ordered rule list; first match wins."""

    def __init__(self, rules: Optional[List[FaultRule]] = None):
        self.rules = list(rules or [])

    def match(self, direction: str, method: Optional[str],
              kind: str) -> Optional[FaultRule]:
        self_id = _fault_self_id()
        for r in self.rules:
            if r.self_id != "*" and r.self_id != self_id:
                continue
            if r.peer != "*" and r.peer != kind:
                continue
            if r.direction != "both" and r.direction != direction:
                continue
            if r.method != "*" and r.method != method:
                continue
            return r
        return None

    @classmethod
    def from_json_dict(cls, d: Dict) -> "FaultSchedule":
        return cls([FaultRule.from_dict(r) for r in d.get("rules", [])])


_INPROC_FAULTS: List[Optional[FaultSchedule]] = [None]
_FAULT_SELF_ID: List[Optional[str]] = [None]
# (next_check_monotonic, schedule, file_mtime)
_fault_file_cache: List[Any] = [0.0, None, None]
FAULT_POLL_S = 0.2


def set_fault_schedule(schedule: Optional[FaultSchedule]) -> None:
    """Install (or clear, with None) an in-process fault schedule. Takes
    precedence over the file-based plane."""
    _INPROC_FAULTS[0] = schedule


def set_fault_self_id(self_id: str) -> None:
    _FAULT_SELF_ID[0] = self_id


def _fault_self_id() -> str:
    sid = _FAULT_SELF_ID[0]
    if sid is None:
        sid = _FAULT_SELF_ID[0] = os.environ.get("RAY_TPU_NODE_ID", "")
    return sid


def fault_file_path() -> Optional[str]:
    path = os.environ.get("RAY_TPU_FAULT_FILE")
    if path:
        return path
    session = os.environ.get("RAY_TPU_SESSION_DIR")
    if session:
        return os.path.join(session, "fault_schedule.json")
    return None


def _load_fault_file() -> Optional[FaultSchedule]:
    if os.environ.get("RAY_TPU_FAULT_INJECTION", "0").lower() not in (
            "1", "true", "yes"):
        return None
    path = fault_file_path()
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        _fault_file_cache[2] = None
        return None
    if mtime == _fault_file_cache[2]:
        return _fault_file_cache[1]
    try:
        import json

        with open(path) as f:
            schedule = FaultSchedule.from_json_dict(json.load(f))
    except Exception:
        return _fault_file_cache[1]  # mid-write; keep the previous rules
    _fault_file_cache[2] = mtime
    return schedule


def faults() -> Optional[FaultSchedule]:
    sched = _INPROC_FAULTS[0]
    if sched is not None:
        return sched
    now = time.monotonic()
    if now >= _fault_file_cache[0]:
        _fault_file_cache[0] = now + FAULT_POLL_S
        _fault_file_cache[1] = _load_fault_file()
    return _fault_file_cache[1]


def _fault_check(direction: str, method: Optional[str],
                 kind: str) -> Optional[FaultRule]:
    sched = faults()
    if sched is None:
        return None
    return sched.match(direction, method, kind)


async def retry_call(fn, *, attempts: Optional[int] = None,
                     base_s: Optional[float] = None,
                     max_s: Optional[float] = None,
                     jitter: float = 0.5,
                     retry_on: Tuple = None,
                     rng: Optional[random.Random] = None):
    """Bounded retry with exponential backoff + jitter for IDEMPOTENT
    control RPCs (ActorDied notifications, re-registration, subscribes).

    `fn` is a zero-arg callable returning a fresh coroutine per attempt
    (a coroutine object can only be awaited once). Retries on transport-
    class failures only by default — an application error (RpcError from
    the handler) means the call ARRIVED and must not be replayed blindly.
    """
    from ray_tpu._private.config import CONFIG

    if attempts is None:
        attempts = CONFIG.rpc_retry_max_attempts
    if base_s is None:
        base_s = CONFIG.rpc_retry_base_s
    if max_s is None:
        max_s = CONFIG.rpc_retry_max_s
    if retry_on is None:
        retry_on = (ConnectionLost, ConnectionError, asyncio.TimeoutError,
                    OSError)
    rng = rng or random
    delay = base_s
    for attempt in range(max(1, attempts)):
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except retry_on:
            if attempt + 1 >= attempts:
                raise
            # full jitter on top of the exponential base: synchronized
            # retry storms from many clients decorrelate
            await asyncio.sleep(delay * (1.0 + jitter * rng.random()))
            delay = min(delay * 2, max_s)


def enable_nodelay(writer: "asyncio.StreamWriter") -> None:
    """TCP_NODELAY on an asyncio transport (no-op for unix sockets).

    Both RPC patterns here lose to Nagle: request/reply frames stall a
    full RTT behind delayed ACKs, and bulk chunk streams serialize behind
    the previous segment. The sync client always set this
    (SyncRpcClient._finish_connect); async transports now match.
    """
    try:
        sock = writer.get_extra_info("socket")
        if sock is not None and sock.family in (socket.AF_INET,
                                                socket.AF_INET6):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except (OSError, AttributeError):
        pass


class RawData:
    """Handler return value carrying a bulk binary payload.

    The server frames it as one msgpack header (``{"r": id, "p": meta,
    "z": len}``) followed by the raw buffer written straight from the
    caller's view — no ``bytes()`` materialization and no msgpack re-pack
    of megabytes (the serve-side double copy of the old chunk path). The
    client read loop sees ``"z"`` and resolves the call future with the
    raw bytes.
    """

    __slots__ = ("view", "meta")

    def __init__(self, view, meta: Any = None):
        self.view = view
        self.meta = meta


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


# ---------------------------------------------------------------------------
# Async server (runs inside agents / head)
# ---------------------------------------------------------------------------


# Frames above this size await transport drain (flow control); smaller frames
# ride the write-combining buffer without touching the socket until the next
# loop tick, so replies/pushes issued in one scheduling burst become one send.
_drain_cache = [0.0, 64 * 1024]  # (last refresh, value)


def _drain_threshold() -> int:
    # cached with a 1s refresh: cheap on the per-frame hot path, while
    # head-broadcast config (applied at registration) still lands quickly
    now = time.monotonic()
    if now - _drain_cache[0] > 1.0:
        try:
            from ray_tpu._private.config import CONFIG

            _drain_cache[1] = CONFIG.rpc_drain_threshold_bytes
        except Exception:
            pass
        _drain_cache[0] = now
    return _drain_cache[1]


class Connection:
    """One accepted connection on the server side.

    Writes are combined: frames queue on a list and one `call_soon` flushes
    them in a single socket send (reference batches via gRPC's own transport;
    here coalescing replaces per-reply write+drain syscalls).
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.meta: Dict[str, Any] = {}  # handshake info (worker id, role, ...)
        self.closed = False
        # mux/shm hook (ISSUE 11): once a session attaches a shm lane,
        # inbound frames route through its demux (session-seq reordering
        # + dispatch via the lane-aware reply connection) instead of the
        # plain per-frame dispatch below. None costs one attribute load.
        self.mux_demux = None
        # fault-injection peer class: accepted TCP sockets report a
        # (host, port) peername, unix sockets a path/empty string
        self.kind = "tcp" if isinstance(
            writer.get_extra_info("peername"), tuple) else "unix"
        self._loop = asyncio.get_event_loop()
        self._outbuf: list = []
        self._buffered = 0
        self._flush_scheduled = False
        # serializes raw (header + sliced body) replies; while one is in
        # flight no ordinary flush may run, or a control frame would land
        # mid-raw-body and corrupt the peer's framing
        self._raw_lock: Optional[asyncio.Lock] = None
        self._raw_sending = False

    def send_nowait(self, msg: Any) -> None:
        if self.closed:
            return
        body = pack(msg)
        rule = _fault_check("out", msg.get("m"), self.kind)
        if rule is not None:
            if rule.action == "drop":
                return  # silently eaten: the peer sees a stall, no RST
            self._loop.call_later(rule.delay_s, self._enqueue, body)
            return
        self._enqueue(body)

    def _enqueue(self, body: bytes) -> None:
        if self.closed:
            return
        self._outbuf.append(body)
        self._buffered += len(body)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_out)

    def _flush_out(self, force: bool = False) -> None:
        """force=True is for the raw sender itself, which flushes queued
        frames ahead of its header while holding the raw lock."""
        self._flush_scheduled = False
        if self._raw_sending and not force:
            # a raw body is mid-write: keep frames queued; the raw sender
            # re-schedules the flush when its body is complete
            return
        self._buffered = 0
        if not self._outbuf or self.closed:
            self._outbuf.clear()
            return
        data = self._outbuf[0] if len(self._outbuf) == 1 else b"".join(self._outbuf)
        STATS["frames_out"] += len(self._outbuf)  # frames, not flushes
        self._outbuf.clear()
        STATS["bytes_out"] += len(data)
        try:
            self.writer.write(data)
        except (ConnectionError, RuntimeError):
            self.closed = True

    async def send(self, msg: Any) -> None:
        if self.closed:
            return
        body = pack(msg)
        rule = _fault_check("out", msg.get("m"), self.kind)
        if rule is not None:
            if rule.action == "drop":
                return
            await asyncio.sleep(rule.delay_s)
        self._outbuf.append(body)
        self._buffered += len(body)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_out)
        if (len(body) >= _drain_threshold()
                or self._buffered >= 4 * _drain_threshold()
                or self._transport_backlog(self.writer) >= 4 * _drain_threshold()):
            # flush NOW so drain sees the bytes (a call_soon flush would run
            # after drain returned un-paused), then apply real backpressure.
            # The transport-backlog check catches slow peers accumulating
            # small frames across many ticks (per-tick _buffered resets).
            self._flush_out()
            try:
                await self.writer.drain()
            except (ConnectionError, RuntimeError):
                self.closed = True

    @staticmethod
    def _transport_backlog(writer) -> int:
        try:
            return writer.transport.get_write_buffer_size()
        except Exception:
            return 0

    async def send_raw(self, req_id: int, raw: RawData) -> None:
        """Reply with header + raw body, sliced with a drain per slice so
        the transport buffer stays near its water marks (one whole-body
        write costs a multi-MB memmove per socket send). Concurrent raw
        replies serialize on a per-connection lock, and `_raw_sending`
        parks ordinary flushes so no control frame splits the body."""
        if self.closed:
            return
        rule = _fault_check("out", None, self.kind)
        if rule is not None:
            if rule.action == "drop":
                return  # the puller's chunk RPC stalls, no RST
            await asyncio.sleep(rule.delay_s)
        if self._raw_lock is None:
            self._raw_lock = asyncio.Lock()
        view = raw.view
        hdr = pack({"r": req_id, "p": raw.meta, "z": len(view)})
        STATS["frames_out"] += 1
        STATS["bytes_out"] += len(hdr) + len(view)
        async with self._raw_lock:
            self._raw_sending = True
            try:
                self._set_bulk_water_marks(self.writer)
                self._flush_out(force=True)  # frame order: queued first
                self.writer.write(hdr)
                for off in range(0, len(view), RAW_WRITE_SLICE):
                    self.writer.write(view[off:off + RAW_WRITE_SLICE])
                    await self.writer.drain()
                await self.writer.drain()
            except (ConnectionError, RuntimeError):
                self.closed = True
            finally:
                self._raw_sending = False
        if self._outbuf and not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_out)

    @staticmethod
    def _set_bulk_water_marks(writer) -> None:
        try:
            writer.transport.set_write_buffer_limits(
                high=RAW_WATER_HIGH, low=RAW_WATER_LOW)
        except Exception:
            pass

    async def push(self, method: str, payload: Any) -> None:
        await self.send({"m": method, "i": 0, "p": payload})

    def push_nowait(self, method: str, payload: Any) -> None:
        """Fire-and-forget push; loop-thread only, write-combined."""
        self.send_nowait({"m": method, "i": 0, "p": payload})

    def close(self) -> None:
        self.closed = True
        try:
            self.writer.close()
        except Exception:
            pass


Handler = Callable[[Connection, Any], Awaitable[Any]]


class RpcServer:
    """Asyncio msgpack-RPC server. Handlers are async callables; returning a
    value sends a reply, raising sends an error reply."""

    def __init__(self, name: str = "server"):
        self.name = name
        # raylint: disable=R10 -- bounded: keys are the method names
        # registered at boot (add_handler), not per-traffic state
        self._handlers: Dict[str, Handler] = {}
        self._on_disconnect: Optional[Callable[[Connection], Awaitable[None]]] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()

    def route(self, method: str):
        def deco(fn: Handler):
            self._handlers[method] = fn
            return fn

        return deco

    def add_handler(self, method: str, fn: Handler) -> None:
        self._handlers[method] = fn

    def set_disconnect_handler(self, fn) -> None:
        self._on_disconnect = fn

    async def start_unix(self, path: str) -> None:
        self._server = await asyncio.start_unix_server(self._accept, path=path)

    async def start_tcp(self, host: str, port: int) -> int:
        self._server = await asyncio.start_server(self._accept, host=host, port=port)
        return self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server:
            self._server.close()
        for conn in list(self.connections):
            conn.close()

    async def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        enable_nodelay(writer)
        conn = Connection(reader, writer)
        self.connections.add(conn)
        try:
            while True:
                hdr = await reader.readexactly(4)
                (length,) = _HDR.unpack(hdr)
                STATS["frames_in"] += 1
                STATS["bytes_in"] += 4 + length
                body = await reader.readexactly(length)
                msg = msgpack.unpackb(body, raw=False, strict_map_key=False)
                rule = _fault_check("in", msg.get("m"), conn.kind)
                if rule is not None:
                    if rule.action == "drop":
                        continue  # frame read, never dispatched
                    await asyncio.sleep(rule.delay_s)
                demux = conn.mux_demux
                if demux is not None:
                    # shm-attached session: the demux restores cross-lane
                    # dispatch order and replies via the lane-aware conn
                    demux.feed_tcp(msg)
                    continue
                hold_task(asyncio.get_running_loop().create_task(
                    self._dispatch(conn, msg)), "rpc-dispatch")
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            conn.closed = True
            self.connections.discard(conn)
            if self._on_disconnect:
                try:
                    await self._on_disconnect(conn)
                except Exception:
                    pass
            conn.close()

    async def _dispatch(self, conn: Connection, msg: Dict) -> None:
        method, req_id, payload = msg.get("m"), msg.get("i", 0), msg.get("p")
        handler = self._handlers.get(method)
        if handler is None:
            if req_id:
                await conn.send({"r": req_id, "e": ["NoSuchMethod", str(method)]})
            return
        try:
            result = await handler(conn, payload)
            if req_id:
                if isinstance(result, RawData):
                    await conn.send_raw(req_id, result)
                else:
                    await conn.send({"r": req_id, "p": result})
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            if req_id:
                await conn.send({"r": req_id, "e": [type(e).__name__, str(e)]})


# ---------------------------------------------------------------------------
# Async client (agent ↔ agent / agent ↔ head)
# ---------------------------------------------------------------------------


class AsyncRpcClient:
    def __init__(self):
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        # req_id -> writable memoryview; a raw reply streams straight into
        # it (call_raw_into), skipping the accumulate-then-copy path
        self._raw_dest: Dict[int, Any] = {}
        self._next_id = 0
        self._push_handler: Optional[Callable[[str, Any], Awaitable[None]]] = None
        self._read_task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._outbuf: list = []
        self._buffered = 0
        self._flush_scheduled = False
        self.connected = False
        self._kind = "tcp"  # fault-injection peer class; set on connect
        # idle-deadline detection (application-level): monotonic stamp of
        # the last inbound frame + the optional monitor task probing a
        # silent channel with pings (partitions don't RST)
        self.last_recv = time.monotonic()
        self._idle_task: Optional[asyncio.Task] = None
        # mux hook (ISSUE 11): seq-stamped ("q") frames of a shm-attached
        # session route through the session's reorder stage; None for
        # every plain client costs one attribute load per frame
        self._mux_feed: Optional[Callable[[Dict], None]] = None
        self._batch_counter = 0

    def next_batch_id(self) -> int:
        """Allocate a BatchItems router id unique on this channel (mux
        streams override this with a session-scoped counter so sibling
        streams sharing one connection can never collide)."""
        self._batch_counter += 1
        return self._batch_counter

    async def connect_tcp(self, host: str, port: int,
                          limit: Optional[int] = None) -> None:
        """`limit` sizes the StreamReader buffer — pass
        DATA_CHANNEL_READER_LIMIT for connections that receive bulk raw
        bodies (the 64 KB default costs ~80 reader wakeups per 5 MB)."""
        if limit:
            self._reader, self._writer = await asyncio.open_connection(
                host, port, limit=limit)
            Connection._set_bulk_water_marks(self._writer)
        else:
            self._reader, self._writer = await asyncio.open_connection(
                host, port)
        enable_nodelay(self._writer)
        self._kind = "tcp"
        self._start(f"rpc-read-{host}:{port}")

    async def connect_unix(self, path: str) -> None:
        self._reader, self._writer = await asyncio.open_unix_connection(path)
        self._kind = "unix"
        self._start(f"rpc-read-{path}")

    def _start(self, label: str = "rpc-read"):
        self.connected = True
        self._loop = asyncio.get_running_loop()
        self._read_task = self._loop.create_task(self._read_loop())
        try:
            self._read_task.set_name(label)  # names the leak in warnings
        except AttributeError:
            pass

    # ------------------------------------------------------ write combining
    def _queue_frame(self, data: bytes) -> None:
        self._outbuf.append(data)
        self._buffered += len(data)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_out)

    def _send_frame(self, data: bytes, method: Optional[str]) -> bool:
        """Fault-aware frame send; returns False when a rule ate it (the
        caller's reply future then pends exactly like a partitioned
        request — timeouts/idle monitors are the recovery path)."""
        rule = _fault_check("out", method, self._kind)
        if rule is not None:
            if rule.action == "drop":
                return False
            self._loop.call_later(rule.delay_s, self._queue_frame, data)
            return True
        self._queue_frame(data)
        return True

    def send_msg_nowait(self, msg: Dict) -> bool:
        """Pack + fault-check + queue one pre-built frame dict (mux
        session flush path — the frame already carries its stream id and
        lane seq). Loop-thread only, write-combined."""
        return self._send_frame(pack(msg), msg.get("m"))

    def register_call(self) -> Tuple[int, "asyncio.Future"]:
        """Allocate a request id + pending reply future WITHOUT sending
        (the mux session frames and routes the request itself). The
        future self-cleans from the pending table when it settles."""
        self._next_id += 1
        req_id = self._next_id
        fut = self._loop.create_future()
        self._pending[req_id] = fut
        fut.add_done_callback(
            lambda _f, rid=req_id: self._pending.pop(rid, None))
        return req_id, fut

    def _flush_out(self) -> None:
        self._flush_scheduled = False
        self._buffered = 0
        if not self._outbuf or self._writer is None:
            self._outbuf.clear()
            return
        data = self._outbuf[0] if len(self._outbuf) == 1 else b"".join(self._outbuf)
        STATS["frames_out"] += len(self._outbuf)  # frames, not flushes
        self._outbuf.clear()
        STATS["bytes_out"] += len(data)
        try:
            self._writer.write(data)
        except (ConnectionError, RuntimeError):
            self.connected = False

    def set_push_handler(self, fn) -> None:
        """Register the handler for unsolicited (push) frames.

        CONTRACT: a *sync* handler runs INLINE in this connection's read
        loop — while it runs, no reply future resolves and no further
        pushed/streamed frame is processed on this connection. Handlers
        must therefore be O(frame): cheap bookkeeping, waking futures,
        enqueueing. Anything heavier (large-value deserialization, user
        callbacks) must be deferred — return a coroutine (async handlers
        get their own task) or hand the work to ``loop.call_soon`` /
        an executor inside the handler.
        """
        self._push_handler = fn

    async def _read_loop(self):
        try:
            while True:
                hdr = await self._reader.readexactly(4)
                (length,) = _HDR.unpack(hdr)
                STATS["frames_in"] += 1
                STATS["bytes_in"] += 4 + length
                body = await self._reader.readexactly(length)
                msg = msgpack.unpackb(body, raw=False, strict_map_key=False)
                rule = _fault_check("in", msg.get("m"), self._kind)
                # last_recv counts only DELIVERED frames: an injected
                # inbound partition must look like silence to the idle
                # monitor, or it could never trip on simulated faults
                if rule is None:
                    self.last_recv = time.monotonic()
                if rule is not None:
                    if rule.action == "delay":
                        await asyncio.sleep(rule.delay_s)
                    else:
                        # drop: consume a raw body (stay framed) but never
                        # resolve the future / run the push handler
                        raw_len = msg.get("z") or 0
                        got = 0
                        while got < raw_len:
                            piece = await self._reader.read(
                                min(raw_len - got, 1 << 20))
                            if not piece:
                                raise asyncio.IncompleteReadError(
                                    b"", raw_len - got)
                            got += len(piece)
                        continue
                raw_len = msg.get("z") if "r" in msg else None
                if raw_len is None:
                    # plain reply or push: one delivery path, optionally
                    # detoured through the mux session's reorder stage
                    # (seq-stamped frames of a shm-attached session)
                    if self._mux_feed is not None and "q" in msg:
                        self._mux_feed(msg)
                    else:
                        self._deliver_msg(msg)
                    continue
                fut = self._pending.pop(msg["r"], None)
                # bulk reply: `z` raw bytes follow the header frame.
                # Read in pieces (readexactly would stall until the
                # WHOLE body sat in the reader buffer — double
                # buffering + a buffer-limit deadlock risk for
                # bodies above the limit). Consumed even when the
                # caller already gave up (timeout popped the
                # future), to stay framed. With a registered dest
                # (call_raw_into) pieces land straight in the
                # caller's buffer — no accumulate-and-join, no
                # second copy.
                dest = self._raw_dest.pop(msg["r"], None)
                direct = dest is not None
                dest_broken = False
                parts, got = [], 0
                try:
                    while got < raw_len:
                        piece = await self._reader.read(
                            min(raw_len - got, 1 << 20))
                        if not piece:
                            raise asyncio.IncompleteReadError(
                                b"", raw_len - got)
                        if direct:
                            if dest_broken or fut is None \
                                    or fut.done():
                                # caller gave up mid-body (cancel/
                                # timeout): its buffer may be
                                # aborted or reused — stop writing,
                                # keep consuming to stay framed
                                pass
                            else:
                                try:
                                    dest[got:got + len(piece)] = \
                                        piece
                                except Exception:
                                    dest_broken = True
                        elif fut is not None and not fut.done():
                            parts.append(piece)
                        got += len(piece)
                except BaseException:
                    # fut was already popped from _pending, so the
                    # loop's generic cleanup can't reach it — fail
                    # it NOW or the caller stalls its full timeout
                    # (forever without one) on a dead connection
                    if fut and not fut.done():
                        fut.set_exception(
                            ConnectionLost("connection lost"))
                    raise
                STATS["bytes_in"] += raw_len
                if fut and not fut.done():
                    if direct and dest_broken:
                        fut.set_exception(RpcError(
                            "raw destination buffer rejected write"))
                    elif direct:
                        fut.set_result(raw_len)  # bytes written
                    else:
                        fut.set_result(
                            parts[0] if len(parts) == 1
                            else b"".join(parts) if parts else b"")
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            self.connected = False
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost("connection lost"))
            self._pending.clear()
            self._raw_dest.clear()

    def _deliver_msg(self, msg: Dict) -> None:
        """Resolve one inbound non-raw frame: a reply settles its pending
        future, a push runs the push handler. Factored out of the read
        loop so a shm lane / mux reorder stage can deliver frames through
        EXACTLY the same path (ISSUE 11)."""
        if "r" in msg:
            fut = self._pending.pop(msg["r"], None)
            if fut and not fut.done():
                if "e" in msg:
                    fut.set_exception(
                        RpcError(f"{msg['e'][0]}: {msg['e'][1]}"))
                else:
                    fut.set_result(msg.get("p"))
        elif self._push_handler:
            # sync handlers run inline (the streamed batch-item
            # path is a hot loop — a task per item would drown the
            # loop); async handlers still get their own task. A
            # handler bug must not kill the read loop — every
            # pending future on this connection would hang.
            try:
                res = self._push_handler(msg.get("m"), msg.get("p"))
                if asyncio.iscoroutine(res):
                    hold_task(self._loop.create_task(res), "push-handler")
            except Exception:
                import logging

                logging.getLogger("ray_tpu").exception(
                    "push handler failed for %s", msg.get("m"))

    def call_future(self, method: str, payload: Any) -> asyncio.Future:
        """Issue a request and return the reply future without awaiting.

        Loop-thread only. Lets callers attach done-callbacks instead of
        spawning a coroutine per request (the driver's task-dispatch hot loop).
        """
        fut = self._loop.create_future()
        if not self.connected:
            # the read loop died (peer gone): a write would be silently
            # dropped by the dead transport and the reply future would
            # hang forever — fail fast so callers can retry post-reconnect
            fut.set_exception(ConnectionLost("not connected"))
            return fut
        self._next_id += 1
        req_id = self._next_id
        self._pending[req_id] = fut
        fut.add_done_callback(lambda _f, rid=req_id: self._pending.pop(rid, None))
        self._send_frame(pack({"m": method, "i": req_id, "p": payload}), method)
        return fut

    async def call(self, method: str, payload: Any, timeout: Optional[float] = None) -> Any:
        if not self.connected:
            raise ConnectionLost("not connected")
        self._next_id += 1
        req_id = self._next_id
        fut = self._loop.create_future()
        self._pending[req_id] = fut
        try:
            body = pack({"m": method, "i": req_id, "p": payload})
            sent = self._send_frame(body, method)
            if sent and (len(body) >= _drain_threshold()
                         or self._buffered >= 4 * _drain_threshold()):
                self._flush_out()
                try:
                    await self._writer.drain()
                except (ConnectionError, RuntimeError):
                    raise ConnectionLost("connection lost")
            if timeout:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(req_id, None)

    async def call_raw_into(self, method: str, payload: Any, dest,
                            timeout: Optional[float] = None) -> Any:
        """call() whose raw (``z``-framed) reply streams DIRECTLY into the
        writable buffer `dest` as pieces arrive — no intermediate bytes
        accumulation, no second copy (the pull pipeline writes each chunk
        reply into the pre-created store view at its offset).

        Returns the byte count written on a raw reply; a plain msgpack
        reply (e.g. None for "absent") comes back as-is. The read loop
        stops touching `dest` the moment this call's future is no longer
        pending, so a cancelled/timed-out caller may safely abort the
        buffer underneath.
        """
        if not self.connected:
            raise ConnectionLost("not connected")
        self._next_id += 1
        req_id = self._next_id
        fut = self._loop.create_future()
        self._pending[req_id] = fut
        self._raw_dest[req_id] = dest
        try:
            self._send_frame(pack({"m": method, "i": req_id, "p": payload}),
                             method)
            if timeout:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(req_id, None)
            self._raw_dest.pop(req_id, None)

    def push_nowait(self, method: str, payload: Any) -> None:
        """One-way fire-and-forget push; loop-thread only, write-combined."""
        self._send_frame(pack({"m": method, "i": 0, "p": payload}), method)

    async def push(self, method: str, payload: Any) -> None:
        body = pack({"m": method, "i": 0, "p": payload})
        if not self._send_frame(body, method):
            return
        if (len(body) >= _drain_threshold()
                or self._buffered >= 4 * _drain_threshold()
                or Connection._transport_backlog(self._writer)
                >= 4 * _drain_threshold()):
            self._flush_out()
            try:
                await self._writer.drain()
            except (ConnectionError, RuntimeError):
                self.connected = False

    def start_idle_monitor(self, idle_s: float,
                           ping_method: str = "Ping") -> None:
        """Application-level idle-deadline detection for long-lived
        channels: a partitioned peer never RSTs, so a pending call can
        otherwise hang for its full (possibly infinite) deadline. While
        calls are outstanding and the channel has been silent past
        `idle_s`, a ping probes it; an unanswered probe declares the
        channel dead and fails every pending future with ConnectionLost.
        A ping that round-trips proves liveness, so a long-running remote
        method never trips this."""
        if idle_s <= 0 or self._idle_task is not None:
            return
        self._idle_task = self._loop.create_task(
            self._idle_monitor(idle_s, ping_method))
        try:
            self._idle_task.set_name("rpc-idle-monitor")
        except AttributeError:
            pass

    async def _idle_monitor(self, idle_s: float, ping_method: str) -> None:
        try:
            while self.connected:
                await asyncio.sleep(max(idle_s / 2, 0.05))
                if not self._pending or not self.connected:
                    continue  # nothing outstanding: silence is fine
                if time.monotonic() - self.last_recv < idle_s:
                    continue
                try:
                    await self.call(ping_method, {}, timeout=idle_s)
                    continue  # alive, just busy
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass
                if not self.connected:
                    return
                self._idle_task = None  # close() must not cancel us
                self.close()
                return
        except asyncio.CancelledError:
            pass

    def close(self) -> None:
        self.connected = False
        if self._idle_task is not None:
            self._idle_task.cancel()
            self._idle_task = None
        if self._read_task:
            # request cancellation; the cancelled task still needs one
            # loop tick to actually finish. aclose() (clean shutdown) and
            # worker.disconnect's final gather consume it — a loop that
            # stops without either emits "Task was destroyed but it is
            # pending!" at teardown.
            self._read_task.cancel()
        # calls issued after the read loop already died registered futures
        # nothing will ever resolve; fail them out
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost("connection closed"))
        self._pending.clear()
        self._raw_dest.clear()
        if self._writer:
            try:
                self._writer.close()
            except Exception:
                pass

    def close_soon(self) -> None:
        """aclose() from a sync call site: schedule a task that awaits the
        cancelled read loop. close() alone leaves a cancelled-but-never-
        awaited task for the dying loop to warn about ("Task was destroyed
        but it is pending!"); the helper task is itself awaited by the
        loop's normal drain."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self.close()
            return
        hold_task(loop.create_task(self.aclose()), "close-soon")

    async def aclose(self) -> None:
        """close() that cancels AND AWAITS the read loop — the clean
        shutdown path (worker.disconnect) must leave no pending task
        behind for the dying loop to warn about."""
        task = self._read_task
        self._read_task = None
        if task is not None and not task.done():
            task.cancel()
        self.close()
        if task is not None and not task.done():
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass


class ConnectionPool:
    """Cached async clients to remote endpoints, keyed by (host, port,
    kind). ``kind="ctrl"`` carries request/reply control traffic;
    ``kind="data"`` is a second socket per peer reserved for bulk chunk
    frames (big reader buffer, bulk water marks), so a megabytes-deep
    transfer never queues ahead of lease/wait frames (reference: the
    object manager's dedicated transfer service). Used by both the node
    agent (peer agents) and the worker (owner/agent direct calls) — ONE
    implementation of the race-guarded connect + replaced-client
    close_soon dance."""

    def __init__(self):
        self._clients: Dict[Tuple[str, int, str], "AsyncRpcClient"] = {}
        self._locks: Dict[Tuple[str, int, str], asyncio.Lock] = {}

    async def get(self, host: str, port: int,
                  kind: str = "ctrl") -> "AsyncRpcClient":
        key = (host, port, kind)
        client = self._clients.get(key)
        if client and client.connected:
            return client
        # per-key lock: two coroutines racing here would both connect and
        # the overwritten loser's read loop would leak as a
        # destroyed-pending task
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            client = self._clients.get(key)
            if client and client.connected:
                return client
            if client is not None:
                client.close_soon()  # await the dead read loop, no warning
            client = AsyncRpcClient()
            await client.connect_tcp(
                host, port,
                limit=DATA_CHANNEL_READER_LIMIT if kind == "data" else None)
            self._clients[key] = client
            return client

    def drop(self, host: str, port: int, kind: Optional[str] = None) -> None:
        """Drop channels to the peer — all kinds by default, or just one
        (a chunk timeout invalidates the data channel, not the peer's
        control traffic)."""
        for key in [k for k in self._clients
                    if k[0] == host and k[1] == port
                    and (kind is None or k[2] == kind)]:
            self._clients.pop(key).close_soon()

    async def aclose_all(self) -> None:
        clients, self._clients = list(self._clients.values()), {}
        for client in clients:
            await client.aclose()


# ---------------------------------------------------------------------------
# Sync client (driver / worker main threads)
# ---------------------------------------------------------------------------


class SyncRpcClient:
    """Blocking RPC client with a background reader thread so server pushes
    (pubsub, object-ready notifications) are delivered while the main thread
    blocks in a call."""

    def __init__(self, push_handler: Optional[Callable[[str, Any], None]] = None):
        self._sock: Optional[socket.socket] = None
        self._pending: Dict[int, "_SyncFuture"] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._push_handler = push_handler
        self._reader_thread: Optional[threading.Thread] = None
        self.connected = False
        self._kind = "tcp"

    def connect_unix(self, path: str, timeout: float = 30.0) -> None:
        self._kind = "unix"
        deadline = time.monotonic() + timeout
        while True:
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(path)
                break
            except (ConnectionRefusedError, FileNotFoundError):
                s.close()
                if time.monotonic() > deadline:
                    raise ConnectionLost(f"could not connect to {path}")
                time.sleep(0.05)
        self._finish_connect(s)

    def connect_tcp(self, host: str, port: int, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while True:
            try:
                s = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise ConnectionLost(f"could not connect to {host}:{port}")
                time.sleep(0.05)
        s.settimeout(None)
        self._finish_connect(s)

    def _finish_connect(self, s: socket.socket) -> None:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1) if s.family != socket.AF_UNIX else None
        self._sock = s
        # raylint: disable=R13 -- happens-before via Thread.start(): the
        # reader thread that later clears this flag is created two lines
        # down, so this write is published to it by the start() barrier
        self.connected = True
        self._reader_thread = threading.Thread(
            target=self._read_loop, daemon=True, name="rpc-reader"
        )
        self._reader_thread.start()

    def _read_loop(self):
        try:
            buf = b""
            while True:
                need = 4
                while len(buf) < need:
                    chunk = self._sock.recv(65536)
                    if not chunk:
                        raise ConnectionLost("eof")
                    buf += chunk
                (length,) = _HDR.unpack(buf[:4])
                need = 4 + length
                while len(buf) < need:
                    chunk = self._sock.recv(65536)
                    if not chunk:
                        raise ConnectionLost("eof")
                    buf += chunk
                msg = msgpack.unpackb(buf[4:need], raw=False, strict_map_key=False)
                buf = buf[need:]
                rule = _fault_check("in", msg.get("m"), self._kind)
                if rule is not None:
                    if rule.action == "drop":
                        continue
                    time.sleep(rule.delay_s)
                if "r" in msg:
                    with self._lock:
                        fut = self._pending.pop(msg["r"], None)
                    if fut:
                        if "e" in msg:
                            fut.set_error(RpcError(f"{msg['e'][0]}: {msg['e'][1]}"))
                        else:
                            fut.set_result(msg.get("p"))
                elif self._push_handler:
                    try:
                        self._push_handler(msg.get("m"), msg.get("p"))
                    except Exception:
                        pass
        except (ConnectionLost, OSError):
            # raylint: disable=R13 -- monotonic one-way flag: only ever
            # flipped True->False after connect; GIL-atomic bool store,
            # and a racy read on another thread just retries the call
            self.connected = False
            with self._lock:
                for fut in self._pending.values():
                    fut.set_error(ConnectionLost("connection lost"))
                self._pending.clear()

    def call(self, method: str, payload: Any, timeout: Optional[float] = None) -> Any:
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            fut = _SyncFuture()
            self._pending[req_id] = fut
        try:
            data = pack({"m": method, "i": req_id, "p": payload})
            rule = _fault_check("out", method, self._kind)
            if rule is not None and rule.action == "drop":
                return fut.result(timeout)  # request eaten: wait it out
            if rule is not None:
                time.sleep(rule.delay_s)
            with self._send_lock:
                self._sock.sendall(data)
            return fut.result(timeout)
        finally:
            with self._lock:
                self._pending.pop(req_id, None)

    def push(self, method: str, payload: Any) -> None:
        data = pack({"m": method, "i": 0, "p": payload})
        rule = _fault_check("out", method, self._kind)
        if rule is not None:
            if rule.action == "drop":
                return
            time.sleep(rule.delay_s)
        with self._send_lock:
            self._sock.sendall(data)

    def close(self) -> None:
        self.connected = False
        if self._sock:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass


class _SyncFuture:
    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error = None

    def set_result(self, value):
        self._result = value
        self._event.set()

    def set_error(self, err):
        self._error = err
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("rpc call timed out")
        if self._error:
            raise self._error
        return self._result
