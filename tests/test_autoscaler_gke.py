"""GKE TPU pod-slice autoscaling (reference:
python/ray/autoscaler/_private/gcp/node_provider.py; SURVEY §7 phase 8;
VERDICT r1 item 8 — a v5e-16 slice scales up and down as ONE unit)."""

import os
import time

import pytest

# actor-creation involves a fresh worker process (jax import ~10s+) per
# actor; 5 local nodes on a 1-CPU box need more than the default 120s
os.environ.setdefault("RAY_TPU_ACTOR_CREATION_TIMEOUT_MS", "420000")

import ray_tpu
from ray_tpu.autoscaler.gke import (
    GkeTpuPodSliceProvider, TPU_TOPOLOGIES, slice_shape)
from ray_tpu.cluster_utils import AutoscalingCluster


def test_topology_table():
    assert slice_shape("v5e-16") == (4, 4)
    with pytest.raises(ValueError):
        slice_shape("v9z-1")


def test_v5e16_slice_scales_up_and_down_atomically():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 2},
        worker_node_types={
            "tpu_v5e_16": {
                "tpu_topology": "v5e-16",
                "cpus_per_host": 1,
                "min_workers": 0,
                "max_workers": 1,
            },
        },
        idle_timeout_minutes=0.12,
        max_workers=2,
        update_interval_s=0.5,
        provider_cls=GkeTpuPodSliceProvider,
    )
    cluster.start()
    try:
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote(resources={"TPU": 1})
        def poke():
            return 1

        # TPU demand triggers ONE slice launch = 4 hosts
        assert ray_tpu.get(poke.remote(), timeout=300) == 1
        deadline = time.time() + 240
        while time.time() < deadline:
            registered = [n for n in ray_tpu.nodes() if n["alive"]
                          and n.get("labels", {}).get("tpu-slice")]
            if len(registered) >= 4:
                break
            time.sleep(1)

        # the multi-host SPMD pattern (reference tpu.py:356-369): one
        # chip-holding worker actor per slice host — each pins a different
        # host because it holds the host's whole chip allotment
        @ray_tpu.remote(resources={"TPU": 4})
        class HostWorker:
            def node(self):
                import ray_tpu as rt

                return rt.get_runtime_context().get_node_id()

        actors = [HostWorker.remote() for _ in range(2)]
        node_ids = ray_tpu.get([a.node.remote() for a in actors],
                               timeout=420)
        assert len(set(node_ids)) == 2, node_ids
        for a in actors:
            ray_tpu.kill(a)
        time.sleep(1)

        hosts, chips = TPU_TOPOLOGIES["v5e-16"]
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        # head + the driver's own local node + 4 slice hosts
        assert len(alive) == 2 + hosts, alive
        assert cluster.provider.num_slices() == 1

        # slice resource semantics: every host advertises the slice name,
        # host 0 the slice-head resource (reference tpu.py:335-398)
        slice_id = cluster.provider.non_terminated_nodes()[0]
        total = ray_tpu.cluster_resources()
        assert total.get(slice_id) == 4.0
        assert total.get("TPU-v5e-16-head") == 1.0
        assert total.get("TPU") == 16.0

        # idle -> the WHOLE slice terminates together (never partial)
        deadline = time.time() + 120
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            n_slice_hosts = len(
                [n for n in alive if n.get("labels", {}).get("tpu-slice")])
            assert n_slice_hosts in (0, hosts), \
                f"partial slice teardown: {n_slice_hosts} hosts alive"
            if n_slice_hosts == 0:
                break
            time.sleep(1)
        assert n_slice_hosts == 0, "slice never scaled down"
        assert cluster.provider.num_slices() == 0
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_busy_host_pins_whole_slice():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 2},
        worker_node_types={
            "tpu_v5e_8": {
                "tpu_topology": "v5e-8",
                "cpus_per_host": 1,
                "min_workers": 0,
                "max_workers": 1,
            },
        },
        idle_timeout_minutes=0.05,
        max_workers=2,
        update_interval_s=0.5,
        provider_cls=GkeTpuPodSliceProvider,
    )
    cluster.start()
    try:
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote(resources={"TPU": 4})
        def hold(t):
            time.sleep(t)
            return 1

        @ray_tpu.remote(resources={"TPU": 1})
        def poke():
            return 1

        # trigger the slice launch and wait until BOTH hosts registered
        assert ray_tpu.get(poke.remote(), timeout=300) == 1
        deadline = time.time() + 240
        while time.time() < deadline:
            up = [n for n in ray_tpu.nodes() if n["alive"]
                  and n.get("labels", {}).get("tpu-slice")]
            if len(up) >= 2:
                break
            time.sleep(1)
        assert len(up) == 2, "slice never fully registered"

        # one long task occupies ONE host of the 2-host slice
        ref = hold.remote(25)
        time.sleep(15)  # idle timeout (3s) long passed for the other host
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        n_slice_hosts = len(
            [n for n in alive if n.get("labels", {}).get("tpu-slice")])
        assert n_slice_hosts == 2, \
            f"slice partially terminated while one host busy: {n_slice_hosts}"
        assert ray_tpu.get(ref, timeout=120) == 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
