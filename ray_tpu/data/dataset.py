"""Dataset: the user-facing lazy, streaming dataset.

Reference: python/ray/data/dataset.py (5.1k LoC: map_batches, iter_batches
:3599, materialize :4498). A Dataset is an immutable logical-operator chain;
execution happens on consumption through the streaming executor
(SURVEY §3.6 call stack).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data._internal import logical as L
from ray_tpu.data._internal.executor import ExecutorStats, StreamingExecutor
from ray_tpu.data._internal.physical import RefBundle
from ray_tpu.data._internal.planner import optimize, plan
from ray_tpu.data.iterator import DataIterator


class ActorPoolStrategy:
    """compute= for class-based UDFs (reference: data/_internal/compute.py)."""

    is_actor_pool = True

    def __init__(self, size: int = 2, min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self.size = min_size or size


class Dataset:
    def __init__(self, last_op: L.LogicalOperator,
                 max_concurrency: Optional[int] = None):
        self._last_op = last_op
        if max_concurrency is None:
            from ray_tpu.data.context import DataContext

            max_concurrency = \
                DataContext.get_current().max_tasks_in_flight_per_op
        self._max_concurrency = max_concurrency
        self._last_stats: Optional[ExecutorStats] = None

    # ------------------------------------------------------------ transforms
    def _append(self, op: L.LogicalOperator) -> "Dataset":
        return Dataset(op, self._max_concurrency)

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[ActorPoolStrategy] = None,
        fn_args: tuple = (),
        fn_kwargs: Optional[dict] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        concurrency: Optional[int] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        **ray_remote_args,
    ) -> "Dataset":
        if isinstance(fn, type):
            if compute is None:
                compute = ActorPoolStrategy(size=concurrency or 2)
        if num_cpus is not None:
            ray_remote_args["num_cpus"] = num_cpus
        if num_tpus is not None:
            ray_remote_args["num_tpus"] = num_tpus
        spec = L.MapSpec(kind="batches", fn=fn, fn_args=fn_args,
                         fn_kwargs=fn_kwargs, batch_size=batch_size,
                         batch_format=batch_format,
                         fn_constructor_args=fn_constructor_args,
                         fn_constructor_kwargs=fn_constructor_kwargs)
        name = f"MapBatches({getattr(fn, '__name__', type(fn).__name__)})"
        return self._append(L.AbstractMap(
            self._last_op, spec, name, compute=compute,
            ray_remote_args=ray_remote_args))

    def map(self, fn: Callable, **ray_remote_args) -> "Dataset":
        spec = L.MapSpec(kind="rows", fn=fn)
        return self._append(L.AbstractMap(
            self._last_op, spec, f"Map({getattr(fn, '__name__', 'fn')})",
            ray_remote_args=ray_remote_args))

    def flat_map(self, fn: Callable, **ray_remote_args) -> "Dataset":
        spec = L.MapSpec(kind="flat", fn=fn)
        return self._append(L.AbstractMap(
            self._last_op, spec, f"FlatMap({getattr(fn, '__name__', 'fn')})",
            ray_remote_args=ray_remote_args))

    def filter(self, fn: Callable, **ray_remote_args) -> "Dataset":
        spec = L.MapSpec(kind="filter", fn=fn)
        return self._append(L.AbstractMap(
            self._last_op, spec, f"Filter({getattr(fn, '__name__', 'fn')})",
            ray_remote_args=ray_remote_args))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch, _name=name, _fn=fn):
            batch[_name] = np.asarray(_fn(batch))
            return batch

        return self.map_batches(add)

    def _map_blocks(self, fn: Callable, name: str) -> "Dataset":
        spec = L.MapSpec(kind="block", fn=fn)
        return self._append(L.AbstractMap(self._last_op, spec, name))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._map_blocks(
            lambda b: BlockAccessor(b).drop(cols), f"DropColumns{cols}")

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._map_blocks(
            lambda b: BlockAccessor(b).select(cols), f"SelectColumns{cols}")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._map_blocks(
            lambda b: BlockAccessor(b).rename(mapping), "RenameColumns")

    def limit(self, n: int) -> "Dataset":
        return self._append(L.Limit(self._last_op, n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._append(L.AbstractAllToAll(
            self._last_op, "repartition", f"Repartition[{num_blocks}]",
            num_blocks=num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        return self._append(L.AbstractAllToAll(
            self._last_op, "random_shuffle", "RandomShuffle",
            seed=seed, num_blocks=num_blocks))

    def sort(self, key: Union[str, List[str]],
             descending: bool = False) -> "Dataset":
        return self._append(L.AbstractAllToAll(
            self._last_op, "sort", f"Sort[{key}]", key=key,
            descending=descending))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        return self._append(L.Union(
            self._last_op, [o._last_op for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._append(L.Zip(self._last_op, other._last_op))

    def random_sample(self, fraction: float,
                      *, seed: Optional[int] = None) -> "Dataset":
        def sample(batch):
            import zlib

            n = len(next(iter(batch.values()))) if batch else 0
            if seed is None:
                rng = np.random.default_rng()
            else:
                # salt by block content: a bare seed would draw the same
                # mask positions in every block
                first = next(iter(batch.values()))
                salt = zlib.crc32(np.ascontiguousarray(first).tobytes()
                                  if first.dtype != object
                                  else str(first[:4]).encode())
                rng = np.random.default_rng((seed, salt))
            keep = rng.random(n) < fraction
            return {k: v[keep] for k, v in batch.items()}

        return self.map_batches(sample)

    # -------------------------------------------------------------- writes
    def write_parquet(self, path: str) -> None:
        from ray_tpu.data.datasource import write_parquet_fn

        self._consume_write(write_parquet_fn(path), "WriteParquet")

    def write_csv(self, path: str) -> None:
        from ray_tpu.data.datasource import write_csv_fn

        self._consume_write(write_csv_fn(path), "WriteCSV")

    def write_json(self, path: str) -> None:
        from ray_tpu.data.datasource import write_json_fn

        self._consume_write(write_json_fn(path), "WriteJSON")

    def write_tfrecords(self, path: str) -> None:
        from ray_tpu.data.datasource import write_tfrecords_fn

        self._consume_write(write_tfrecords_fn(path), "WriteTFRecords")

    def iter_torch_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_torch_batches(**kw)

    def _consume_write(self, write_fn, name: str) -> None:
        ds = self._append(L.Write(self._last_op, write_fn, name))
        for _ in ds._execute_bundles():
            pass

    # ----------------------------------------------------------- execution
    def _execute_bundles(self, publish: bool = True) -> Iterator[RefBundle]:
        stats = ExecutorStats()
        topo = plan(optimize(self._last_op.chain()),
                    max_concurrency=self._max_concurrency)
        executor = StreamingExecutor(topo, stats).start()
        self._last_stats = stats
        try:
            yield from executor.iter_bundles()
        finally:
            executor.shutdown()
            # publish=False: a windowed consumer (_iter_blocks) keeps
            # pulling blocks AFTER this generator exhausts; it publishes
            # itself once the stall/consume counters are final
            if publish:
                self._publish_stats(stats)

    def _publish_stats(self, stats: ExecutorStats) -> None:
        """Best-effort: per-operator stats land in the head KV so the
        dashboard's /api/data_stats can render them cluster-wide
        (reference: data stats surface in the dashboard's Ray Data tab)."""
        try:
            import json as _json
            import time as _time

            from ray_tpu.experimental.internal_kv import (
                _internal_kv_del, _internal_kv_list, _internal_kv_put)

            # zero-padded ms timestamp first => lexicographic == recency
            key = (f"__data_stats__:{int(_time.time() * 1000):015d}"
                   f":{id(self):x}")
            _internal_kv_put(key.encode(), _json.dumps(
                stats.to_dict()).encode())
            # bound head-KV growth: keep only the most recent entries
            stale = sorted(_internal_kv_list(b"__data_stats__:"))[:-100]
            for k in stale:
                _internal_kv_del(k)
        except Exception:
            pass

    def iter_internal_ref_bundles(self) -> Iterator[RefBundle]:
        return self._execute_bundles()

    def _iter_blocks(self) -> Iterator[Block]:
        """Consumer-edge block stream with pull prefetch (ISSUE 12).

        The old loop blocked on each block's pull in turn — on a
        multi-node pipeline every cross-node block cost a full pull
        latency on the consumer's critical path. Here the next
        ``iter_prefetch_blocks`` bundles' pulls are INITIATED (one
        batched, non-blocking WaitObjects frame) while the current block
        is being consumed, so ``iter_jax_batches`` overlaps network with
        host→device transfer. Stall time that still leaks through is
        reported in ``ExecutorStats.consumer_stall_s``.
        """
        import queue as _queue
        import threading as _threading
        import time as _time

        from ray_tpu.data.context import DataContext

        depth = max(0, DataContext.get_current().iter_prefetch_blocks)
        it = self._execute_bundles(publish=False)
        # Feeder thread: drains bundles AS THE EXECUTOR PRODUCES THEM,
        # initiating each block's pull immediately (off the consumer's
        # critical path, one frame per bundle), and parks them in a
        # bounded window. The consumer below blocks only when NOTHING
        # is available — never on filling the window ahead (a
        # window-first loop would delay every yield behind producer
        # progress, the opposite of overlap).
        q: "_queue.Queue" = _queue.Queue(maxsize=depth + 1)
        DONE = object()
        err: list = []
        stop = _threading.Event()

        def initiate(bundle):
            try:
                from ray_tpu._private import worker as worker_mod

                w = worker_mod.global_worker
                if w is not None and w.connected:
                    w._prefetch_plasma([bundle.block_ref], min_need=1)
            except Exception:
                pass  # prefetch is advisory; get() below is the contract

        def feeder():
            try:
                for bundle in it:
                    initiate(bundle)
                    while not stop.is_set():
                        try:
                            q.put(bundle, timeout=0.1)
                            break
                        except _queue.Full:
                            continue
                    if stop.is_set():
                        break
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err.append(e)
            finally:
                if stop.is_set():
                    # abandoned consumer: close the source HERE (the
                    # generator is owned by this thread) so the
                    # executor tears down instead of leaking
                    try:
                        it.close()
                    except Exception:
                        pass
                # DONE must reach a still-draining consumer even if the
                # window is momentarily full (e.g. feeder errored with a
                # full queue) — a dropped sentinel wedges q.get() forever
                while True:
                    try:
                        q.put(DONE, timeout=0.1)
                        break
                    except _queue.Full:
                        if stop.is_set():
                            break

        t = _threading.Thread(target=feeder, daemon=True,
                              name="raytpu-data-ingest")
        t.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    if err:
                        raise err[0]
                    return
                # stall = time blocked in the PULL only (the stat's
                # contract); producer wait shows up as executor wall
                t0 = _time.perf_counter()
                block = ray_tpu.get(item.block_ref)
                stall = _time.perf_counter() - t0
                stats = self._last_stats
                if stats is not None:
                    stats.consumer_stall_s += stall
                    stats.blocks_consumed += 1
                yield block
        finally:
            stop.set()
            t.join(timeout=5)
            if self._last_stats is not None:
                self._publish_stats(self._last_stats)

    def iterator(self) -> DataIterator:
        return DataIterator(self._iter_blocks, stats_fn=self.stats)

    # ---------------------------------------------------------- consumption
    def iter_batches(self, **kw) -> Iterator[Any]:
        return self.iterator().iter_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_jax_batches(**kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return self.iterator().iter_rows()

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def take_batch(self, batch_size: int = 20,
                   batch_format: str = "numpy") -> Any:
        for b in self.limit(batch_size).iter_batches(
                batch_size=batch_size, batch_format=batch_format,
                prefetch_batches=0):
            return b
        return {}

    def count(self) -> int:
        return sum(b.meta.num_rows for b in self._execute_bundles())

    def schema(self) -> Optional[Dict[str, str]]:
        for bundle in self._execute_bundles():
            if bundle.meta.schema:
                return bundle.meta.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s) if s else []

    def to_pandas(self):
        import pandas as pd

        dfs = [BlockAccessor(b).to_pandas() for b in self._iter_blocks()]
        if not dfs:
            return pd.DataFrame()
        return pd.concat(dfs, ignore_index=True)

    def to_arrow(self):
        return BlockAccessor(
            BlockAccessor.concat(list(self._iter_blocks()))).to_arrow()

    def materialize(self) -> "MaterializedDataset":
        bundles = [(b.block_ref, b.meta) for b in self._execute_bundles()]
        return MaterializedDataset(
            L.InputData(bundles), self._max_concurrency)

    # simple aggregates
    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof=ddof))

    def aggregate(self, *aggs: agg_mod.AggregateFn):
        ds = self._append(L.AbstractAllToAll(
            self._last_op, "global_agg", "Aggregate", aggs=list(aggs)))
        rows = ds.take_all()
        row = rows[0] if rows else {}
        vals = [row.get(a.output_name(None)) for a in aggs]
        return vals[0] if len(vals) == 1 else tuple(vals)

    # ----------------------------------------------------------- splitting
    def split(self, n: int, *, equal: bool = False) -> List["MaterializedDataset"]:
        mat = self.materialize()
        bundles = mat._last_op.bundles
        if equal:
            total = sum(m.num_rows for _, m in bundles)
            per = total // n
            mat2 = mat.repartition(n) if per else mat
            rows_target = [per] * n
            blocks = list(mat2._iter_blocks())
            merged = BlockAccessor.concat(blocks)
            acc = BlockAccessor(merged)
            out = []
            pos = 0
            for t in rows_target:
                out.append(from_blocks([acc.slice(pos, pos + t)]))
                pos += t
            return out
        groups: List[List[Tuple[Any, BlockMetadata]]] = [[] for _ in range(n)]
        for i, b in enumerate(bundles):
            groups[i % n].append(b)
        return [MaterializedDataset(L.InputData(g), self._max_concurrency)
                for g in groups]

    def split_at_indices(self, indices: List[int]
                         ) -> List["MaterializedDataset"]:
        """Split at global row offsets (reference: dataset.py
        split_at_indices): [3, 8] → rows [0,3), [3,8), [8,end)."""
        if any(i < 0 for i in indices) or list(indices) != sorted(indices):
            raise ValueError(
                f"indices must be non-negative and sorted; got {indices}")
        mat = self.materialize()
        blocks = list(mat._iter_blocks())
        merged = BlockAccessor.concat(blocks)
        acc = BlockAccessor(merged)
        total = acc.num_rows()
        out = []
        bounds = [0] + [min(i, total) for i in indices] + [total]
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            out.append(from_blocks([acc.slice(lo, hi)]))
        return out

    def split_proportionately(self, proportions: List[float]
                              ) -> List["MaterializedDataset"]:
        """Split by fractions; the remainder forms the final split
        (reference: dataset.py split_proportionately)."""
        if not proportions or any(p <= 0 for p in proportions) or \
                sum(proportions) >= 1.0:
            raise ValueError(
                "proportions must be positive and sum to < 1 "
                f"(the remainder is the last split); got {proportions}")
        mat = self.materialize()  # one execution feeds count AND split
        total = mat.count()
        indices = []
        acc = 0.0
        for p in proportions:
            acc += p
            indices.append(int(total * acc))
        return mat.split_at_indices(indices)

    def train_test_split(self, test_size: Union[float, int], *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["MaterializedDataset",
                                    "MaterializedDataset"]:
        """(train, test) split (reference: dataset.py train_test_split)."""
        ds: Dataset = self
        if shuffle:
            ds = ds.random_shuffle(seed=seed)
        mat = ds.materialize()  # one execution feeds count AND split
        total = mat.count()
        if isinstance(test_size, float):
            if not 0.0 < test_size < 1.0:
                raise ValueError("float test_size must be in (0, 1)")
            n_test = int(total * test_size)
        else:
            if not 0 < test_size < total:
                raise ValueError(
                    f"int test_size must be in (0, {total})")
            n_test = int(test_size)
        train, test = mat.split_at_indices([total - n_test])
        return train, test

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (reference: dataset.py unique)."""
        seen = set()
        out = []
        for batch in self.iter_batches(batch_format="numpy"):
            for v in batch[column]:
                key = v.item() if hasattr(v, "item") else v
                if key not in seen:
                    seen.add(key)
                    out.append(key)
        return out

    def to_torch(self, **iter_kwargs):
        """A torch IterableDataset over this Dataset's batches
        (reference: dataset.py to_torch; batches come through
        iter_torch_batches so dtype/device handling stays in one place)."""
        import torch

        outer = self

        class _TorchIterable(torch.utils.data.IterableDataset):
            def __iter__(self):
                return outer.iter_torch_batches(**iter_kwargs)

        return _TorchIterable()

    def streaming_split(self, n: int, *, equality: bool = False,
                        locality_hints=None) -> List[DataIterator]:
        """N coordinated iterators backed by one execution (reference:
        dataset.streaming_split / _internal/execution/operators/
        output_splitter.py). A coordinator actor runs the stream and hands
        out bundles round-robin; per-consumer iterators pull from it."""
        coordinator = _SplitCoordinator.options(name=None).remote(
            _serialize_plan(self), n)

        def make_block_fn(idx: int):
            def block_fn():
                import time as _time

                # client-side epoch counter; the coordinator gates epoch
                # starts on ALL consumers having drained the previous one.
                epoch = getattr(block_fn, "_epoch", 0)
                block_fn._epoch = epoch + 1
                while True:
                    ref = ray_tpu.get(coordinator.next_ref.remote(idx, epoch))
                    if ref is None:
                        return
                    if ref == "WAIT":
                        _time.sleep(0.02)
                        continue
                    yield ray_tpu.get(ref)

            return block_fn

        return [DataIterator(make_block_fn(i)) for i in range(n)]

    def show(self, limit: int = 20) -> None:
        """Print up to ``limit`` rows (reference: Dataset.show)."""
        for row in self.take(limit):
            print(row)

    # ------------------------------------------------------------- misc
    def stats(self) -> str:
        return self._last_stats.summary() if self._last_stats else ""

    def num_blocks(self) -> Optional[int]:
        op = self._last_op
        if isinstance(op, L.InputData):
            return len(op.bundles)
        if isinstance(op, L.Read):
            return len(op.read_tasks)
        return None

    def __repr__(self):
        names = [op.name for op in self._last_op.chain()]
        return f"Dataset({' -> '.join(names)})"

    # pickling a Dataset ships the logical plan (used by trainers)
    def __reduce__(self):
        return (Dataset, (self._last_op, self._max_concurrency))


class MaterializedDataset(Dataset):
    """Fully-executed dataset: blocks pinned in the object store."""

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._last_op.bundles)


class GroupedData:
    """Reference: python/ray/data/grouped_data.py."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: agg_mod.AggregateFn) -> Dataset:
        return self._ds._append(L.AbstractAllToAll(
            self._ds._last_op, "groupby_agg", f"GroupBy[{self._key}]",
            key=self._key, aggs=list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(agg_mod.Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(agg_mod.Mean(on))

    def map_groups(self, fn: Callable) -> Dataset:
        """Sort by key, then apply fn per contiguous group."""
        key = self._key

        def apply_groups(batch):
            col = batch[key]
            uniq, inverse = np.unique(col, return_inverse=True)
            outs = []
            for g in range(len(uniq)):
                mask = inverse == g
                out = fn({k: v[mask] for k, v in batch.items()})
                outs.append(out)
            merged: Dict[str, list] = {}
            for o in outs:
                for k, v in o.items():
                    merged.setdefault(k, []).append(np.asarray(v))
            return {k: np.concatenate(v) for k, v in merged.items()}

        return self._ds.repartition(1).map_batches(apply_groups)


# ---------------------------------------------------------- split coordinator
def _serialize_plan(ds: Dataset) -> bytes:
    import cloudpickle

    return cloudpickle.dumps(ds)


@ray_tpu.remote
class _SplitCoordinator:
    """Owns one streaming execution per epoch; consumers pull bundles
    round-robin. Epoch e+1 starts only after every consumer drained epoch e
    — a consumer arriving early gets "WAIT" so it never observes an empty
    epoch (reference: output_splitter.py's epoch barrier)."""

    def __init__(self, plan_blob: bytes, n: int):
        import cloudpickle

        self._ds: Dataset = cloudpickle.loads(plan_blob)
        self._n = n
        self._epoch = -1
        self._gen = None
        self._queues: List[List] = [[] for _ in range(n)]
        self._done = False
        self._rr = 0
        self._finished = set(range(n))  # everyone "drained" epoch -1
        # Hand-outs stay pinned until the consumer's NEXT request: a
        # consumer fetches each block before asking for another, so holding
        # the last two refs per consumer keeps fetches safe while bounding
        # object-store usage (instead of pinning the whole epoch).
        import collections as _c

        self._hold: List = [_c.deque(maxlen=2) for _ in range(n)]

    def _start_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        import collections as _c

        self._gen = self._ds._execute_bundles()
        self._queues = [[] for _ in range(self._n)]
        self._hold = [_c.deque(maxlen=2) for _ in range(self._n)]
        self._done = False
        self._rr = 0
        self._finished = set()

    def next_ref(self, idx: int, epoch: int):
        if epoch > self._epoch:
            if len(self._finished) == self._n:
                self._start_epoch(epoch)
            else:
                return "WAIT"  # peers still draining the previous epoch
        elif epoch < self._epoch:
            return None  # stale consumer; its epoch is gone
        while not self._queues[idx] and not self._done:
            try:
                bundle = next(self._gen)
            except StopIteration:
                self._done = True
                break
            self._queues[self._rr].append(bundle.block_ref)
            self._rr = (self._rr + 1) % self._n
        if self._queues[idx]:
            ref = self._queues[idx].pop(0)
            self._hold[idx].append(ref)
            return ref
        if self._done and not self._queues[idx]:
            self._finished.add(idx)
        return None


def from_blocks(blocks: List[Block]) -> MaterializedDataset:
    bundles = []
    for b in blocks:
        acc = BlockAccessor(b)
        bundles.append((ray_tpu.put(b), acc.metadata()))
    return MaterializedDataset(L.InputData(bundles))
