"""Replay buffers (reference: rllib/utils/replay_buffers/ —
EpisodeReplayBuffer / PrioritizedEpisodeReplayBuffer used by DQN/SAC).

Flat numpy ring buffers over transitions: contiguous arrays keep sampling a
single fancy-index gather, and the sampled minibatch ships to the learner as
one host→HBM transfer.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring buffer of (obs, action, reward, next_obs, done)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._storage: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def _init_storage(self, batch: Dict[str, np.ndarray]) -> None:
        self._storage = {
            k: np.empty((self.capacity,) + v.shape[1:], v.dtype)
            for k, v in batch.items()
        }

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """Append N transitions given as row-stacked arrays."""
        n = len(next(iter(batch.values())))
        if self._storage is None:
            self._init_storage(batch)
        for k, v in batch.items():
            store = self._storage[k]
            first = min(n, self.capacity - self._idx)
            store[self._idx:self._idx + first] = v[:first]
            if first < n:  # wrap
                store[: n - first] = v[first:]
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    utils/replay_buffers/prioritized_episode_buffer.py; PER, Schaul 2015).
    Priorities default to max-seen so new transitions are sampled soon."""

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4,
                 seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._prios = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        start = self._idx
        super().add_batch(batch)
        idx = (start + np.arange(n)) % self.capacity
        self._prios[idx] = self._max_prio

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        p = self._prios[: self._size] ** self.alpha
        p = p / p.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=p)
        weights = (self._size * p[idx]) ** (-self.beta)
        out = {k: v[idx] for k, v in self._storage.items()}
        out["weights"] = (weights / weights.max()).astype(np.float32)
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx: np.ndarray, prios: np.ndarray) -> None:
        prios = np.abs(prios) + 1e-6
        self._prios[idx] = prios
        self._max_prio = max(self._max_prio, float(prios.max()))


class SequenceReplayBuffer:
    """Fixed-length-sequence replay with stored recurrent state
    (reference: rllib/algorithms/r2d2 — replay of [B, T] sequences whose
    LSTM state at sequence start was recorded at collection time, so the
    learner resumes the net mid-episode instead of from zeros; Kapturowski
    2019 "stored state" strategy).

    Each stored item is one sequence: time-major arrays [T, ...] plus the
    (h, c) state at t=0. Sampling returns batch-major [B, T, ...] arrays
    and stacked states — one contiguous host->HBM transfer, same design
    rationale as the flat buffer above.
    """

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity  # in sequences
        self._storage: Optional[Dict[str, np.ndarray]] = None
        self._state_storage: Optional[tuple] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_sequences(self, batch: Dict[str, np.ndarray],
                      state_in: tuple) -> None:
        """batch: time-major [T, N, ...] arrays (one fragment, N envs);
        state_in: per-env (h, c) at t=0, each [N, cell]."""
        n = next(iter(batch.values())).shape[1]
        if self._storage is None:
            self._storage = {
                k: np.empty((self.capacity,) + v.shape[:1] + v.shape[2:],
                            v.dtype)
                for k, v in batch.items()}
            self._state_storage = tuple(
                np.zeros((self.capacity,) + s.shape[1:], np.float32)
                for s in state_in)
        for j in range(n):  # sequences land as independent items
            for k, v in batch.items():
                self._storage[k][self._idx] = v[:, j]
            for store, s in zip(self._state_storage, state_in):
                store[self._idx] = s[j]
            self._idx = (self._idx + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        out = {k: v[idx] for k, v in self._storage.items()}
        out["state_in"] = tuple(s[idx] for s in self._state_storage)
        return out
