"""Spanning broadcast trees over the per-peer data channels (ISSUE 9).

When K consumers pull the same large object, N serial point-to-point
pulls cost the root N full uploads. This module arranges the consumers
into a spanning tree (reference: the object manager's push path,
``object_manager.h`` — here coordinated by the head instead of gossip):

- ``BcastTreeRegistry`` (head-side, in-memory): assigns each joining
  consumer a parent — the shallowest live node with spare fanout — so
  tree depth is O(log_fanout N) and no node uploads more than ``fanout``
  copies. On a node-death verdict (the PR 5 machinery) or a consumer-
  reported dead parent, a dead interior node's children re-parent to its
  closest live ancestor (ultimately a root holder).
- ``TransferProgress`` (agent-side): byte-interval tracking of an
  in-flight pull so an interior node can RELAY chunks it has already
  received while still receiving the rest — children stream behind their
  parent at chunk granularity instead of waiting for the full object.
- ``bcast_fetch`` (agent-side): the consumer loop — join, pull from the
  assigned parent, re-parent on failure, fall back to the plain striped
  pull if the head or the tree is unavailable. Broadcast is an
  optimization layer: every failure mode degrades to the PR 3 pull
  plane, never to a hang.

Registry state is deliberately not WAL-durable: it describes transfers
in flight, and a head restart simply starts fresh trees (consumers fall
back to direct pulls mid-outage).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import CONFIG


def addr_key(addr: Dict) -> str:
    return f"{addr.get('host')}:{addr.get('port')}"


# ---------------------------------------------------------------------------
# agent-side: in-flight transfer progress (chunk-level relay)
# ---------------------------------------------------------------------------
class TransferProgress:
    """Byte intervals of one in-flight pull, awaitable by relay serves.

    Registered in ``PullManager.active`` the moment a node decides to
    pull (before the transfer is admitted), so a child assigned to this
    node parks on ``wait_covered`` through the parent's own admission
    delay. ``reset`` re-arms it when a retry allocates a fresh store
    view (marks from an aborted attempt describe memory that no longer
    exists).
    """

    def __init__(self, hex_id: str, size: int):
        self.hex_id = hex_id
        self.size = size
        self.view: Optional[memoryview] = None
        self.failed = False
        self._intervals: List[List[int]] = []  # merged, sorted [start, end)
        self._waiters: List[Tuple[int, int, asyncio.Future]] = []

    # -- write side (the pulling stripes) -----------------------------------
    def reset(self, view: memoryview) -> None:
        self.view = view
        self.failed = False
        self._intervals = []

    def mark(self, off: int, length: int) -> None:
        if length <= 0:
            return
        start, end = off, off + length
        iv = self._intervals
        i = 0
        while i < len(iv) and iv[i][1] < start:
            i += 1
        j = i
        while j < len(iv) and iv[j][0] <= end:
            start = min(start, iv[j][0])
            end = max(end, iv[j][1])
            j += 1
        iv[i:j] = [[start, end]]
        self._wake()

    def fail(self) -> None:
        """Transfer over (aborted, or sealed-and-unregistered): wake every
        waiter; each re-checks the store before giving up."""
        self.failed = True
        self.view = None
        self._wake()

    # -- read side (relay serves) -------------------------------------------
    def covered(self, off: int, length: int) -> bool:
        end = min(off + length, self.size)
        if end <= off:
            return True
        for start, stop in self._intervals:  # merged + sorted: the only
            if start > off:                  # candidate is the one
                return False                 # containing `off`
            if stop >= end:
                return True
        return False

    async def wait_covered(self, off: int, length: int,
                           timeout: float) -> bool:
        if self.covered(off, length) and self.view is not None:
            return True
        if self.failed:
            return False
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((off, min(off + length, self.size), fut))
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return False
        finally:
            self._waiters = [w for w in self._waiters if w[2] is not fut]
        return self.covered(off, length) and self.view is not None

    def _wake(self) -> None:
        for off, end, fut in self._waiters:
            if fut.done():
                continue
            if self.failed or (self.covered(off, end - off)
                               and self.view is not None):
                fut.set_result(True)

    def stats(self) -> Dict:
        done = sum(e - s for s, e in self._intervals)
        return {"size": self.size, "bytes_done": done,
                "waiters": len(self._waiters), "failed": self.failed}


# ---------------------------------------------------------------------------
# head-side: tree registry
# ---------------------------------------------------------------------------
class _TreeNode:
    __slots__ = ("key", "addr", "parent", "children", "state", "depth",
                 "seq")

    def __init__(self, key: str, addr: Dict, parent: Optional[str],
                 state: str, depth: int, seq: int):
        self.key = key
        self.addr = dict(addr)
        self.parent = parent          # parent key, None for roots
        self.children: set = set()    # child keys
        self.state = state            # 'root' | 'joining' | 'ready' | 'dead'
        self.depth = depth
        self.seq = seq


class _Tree:
    __slots__ = ("object_id", "size", "nodes", "last_touch", "joins",
                 "reparents", "seq")

    def __init__(self, object_id: str, size: int):
        self.object_id = object_id
        self.size = size
        self.nodes: Dict[str, _TreeNode] = {}
        self.last_touch = time.monotonic()
        self.joins = 0
        self.reparents = 0
        self.seq = 0


class BcastTreeRegistry:
    """Head-owned assignment of consumers into per-object spanning trees.

    Pure in-memory bookkeeping on the head loop (single-threaded); every
    reply is advisory — a consumer that cannot reach its parent comes
    back with ``reparent`` and the registry converges around the death.
    """

    def __init__(self):
        self.trees: Dict[str, _Tree] = {}
        self.joins_total = 0
        self.reparents_total = 0

    # -- public API (one RPC handler each) ----------------------------------
    def join(self, object_id: str, size: int, addr: Dict,
             roots: List[Dict]) -> Dict:
        self._gc()
        tree = self.trees.get(object_id)
        if tree is None:
            tree = self.trees[object_id] = _Tree(object_id, size)
        tree.last_touch = time.monotonic()
        for root in roots or []:
            rk = addr_key(root)
            node = tree.nodes.get(rk)
            if node is None:
                tree.seq += 1
                tree.nodes[rk] = _TreeNode(rk, root, None, "root", 0,
                                           tree.seq)
            elif node.state == "dead":
                pass  # a dead root stays dead until re-advertised alive
        key = addr_key(addr)
        existing = tree.nodes.get(key)
        if existing is not None and existing.state != "dead":
            # idempotent re-join (retried RPC): same slot, parent healed
            # if necessary
            if existing.parent is not None:
                parent = tree.nodes.get(existing.parent)
                if parent is None or parent.state == "dead":
                    return self._reattach(tree, existing)
            return self._slot_reply(tree, existing)
        parent = self._pick_parent(tree, exclude=key)
        if parent is None:
            return {"fallback": "no live holder in tree"}
        tree.seq += 1
        tree.joins += 1
        self.joins_total += 1
        node = _TreeNode(key, addr, parent.key, "joining",
                         parent.depth + 1, tree.seq)
        if existing is not None:      # dead slot being re-taken
            tree.nodes.pop(key, None)
        tree.nodes[key] = node
        parent.children.add(key)
        return self._slot_reply(tree, node)

    def ready(self, object_id: str, addr: Dict) -> Dict:
        tree = self.trees.get(object_id)
        if tree is None:
            return {"ok": False}
        tree.last_touch = time.monotonic()
        node = tree.nodes.get(addr_key(addr))
        if node is not None and node.state == "joining":
            node.state = "ready"
        return {"ok": True}

    def reparent(self, object_id: str, addr: Dict,
                 dead_addr: Dict) -> Dict:
        """Consumer ``addr`` observed its parent ``dead_addr`` failing:
        mark it dead, hoist its children to the closest live ancestor,
        and hand the caller its new slot."""
        tree = self.trees.get(object_id)
        if tree is None:
            return {"fallback": "tree expired"}
        tree.last_touch = time.monotonic()
        self._mark_dead(tree, addr_key(dead_addr))
        node = tree.nodes.get(addr_key(addr))
        if node is None or node.state == "dead":
            return self.join(object_id, tree.size, addr, [])
        tree.reparents += 1
        self.reparents_total += 1
        return self._reattach(tree, node)

    def on_node_removed(self, addr: Dict) -> None:
        """Cluster-level death verdict: fail the node out of every tree
        NOW so joiners stop being routed to it (its children re-parent
        proactively instead of waiting out chunk timeouts)."""
        key = addr_key(addr)
        for tree in self.trees.values():
            if key in tree.nodes:
                self._mark_dead(tree, key)

    def stats(self, object_id: Optional[str] = None) -> Dict:
        def one(tree: _Tree) -> Dict:
            states: Dict[str, int] = {}
            for n in tree.nodes.values():
                states[n.state] = states.get(n.state, 0) + 1
            return {
                "size": tree.size,
                "nodes": len(tree.nodes),
                "states": states,
                "depth_max": max(
                    (n.depth for n in tree.nodes.values()
                     if n.state != "dead"), default=0),
                "joins": tree.joins,
                "reparents": tree.reparents,
                "edges": {k: sorted(n.children)
                          for k, n in tree.nodes.items() if n.children},
            }

        if object_id is not None:
            tree = self.trees.get(object_id)
            return one(tree) if tree else {}
        return {
            "trees": len(self.trees),
            "joins_total": self.joins_total,
            "reparents_total": self.reparents_total,
            "objects": {oid: one(t) for oid, t in self.trees.items()},
        }

    # -- internals -----------------------------------------------------------
    def _slot_reply(self, tree: _Tree, node: _TreeNode) -> Dict:
        parent = tree.nodes.get(node.parent) if node.parent else None
        if parent is None:
            return {"fallback": "no live holder in tree"}
        return {"parent": dict(parent.addr), "depth": node.depth,
                "parent_state": parent.state}

    def _pick_parent(self, tree: _Tree,
                     exclude: Optional[str] = None) -> Optional[_TreeNode]:
        """Shallowest live node with spare fanout; FIFO (seq) among
        equals so early joiners fill before late ones."""
        fanout = max(1, CONFIG.bcast_fanout)
        best = None
        for n in tree.nodes.values():
            if n.state == "dead" or n.key == exclude:
                continue
            if len(n.children) >= fanout:
                continue
            if best is None or (n.depth, len(n.children), n.seq) < (
                    best.depth, len(best.children), best.seq):
                best = n
        return best

    def _live_ancestor(self, tree: _Tree,
                       node: _TreeNode) -> Optional[_TreeNode]:
        seen = set()
        cur = node.parent
        while cur is not None and cur not in seen:
            seen.add(cur)
            anc = tree.nodes.get(cur)
            if anc is None:
                return None
            if anc.state != "dead":
                return anc
            cur = anc.parent
        return None

    def _mark_dead(self, tree: _Tree, key: str) -> None:
        node = tree.nodes.get(key)
        if node is None or node.state == "dead":
            return
        node.state = "dead"
        parent = tree.nodes.get(node.parent) if node.parent else None
        if parent is not None:
            parent.children.discard(key)
        # hoist the orphaned subtree roots to their closest live
        # ancestor (may exceed fanout transiently — bounded by deaths,
        # and a better slot is found at the next natural re-join)
        for child_key in sorted(node.children):
            child = tree.nodes.get(child_key)
            if child is None or child.state == "dead":
                continue
            anc = self._live_ancestor(tree, child)
            if anc is None:
                child.parent = None  # next touch falls back / re-joins
                continue
            child.parent = anc.key
            anc.children.add(child_key)
            self._redepth(tree, child, anc.depth + 1)
        node.children = set()

    def _redepth(self, tree: _Tree, node: _TreeNode, depth: int) -> None:
        node.depth = depth
        stack = [node]
        seen = {node.key}
        while stack:
            cur = stack.pop()
            for ck in cur.children:
                child = tree.nodes.get(ck)
                if child is None or ck in seen:
                    continue
                seen.add(ck)
                child.depth = cur.depth + 1
                stack.append(child)

    def _reattach(self, tree: _Tree, node: _TreeNode) -> Dict:
        parent = None
        if node.parent is not None:
            anc = tree.nodes.get(node.parent)
            if anc is not None and anc.state != "dead":
                parent = anc
        if parent is None:
            parent = self._pick_parent(tree, exclude=node.key)
        if parent is None:
            return {"fallback": "no live holder in tree"}
        # guard: never attach under our own subtree (possible when the
        # picker chose a descendant after heavy churn)
        probe, seen = parent, set()
        while probe is not None and probe.key not in seen:
            if probe.key == node.key:
                return {"fallback": "no acyclic slot"}
            seen.add(probe.key)
            probe = tree.nodes.get(probe.parent) if probe.parent else None
        old = tree.nodes.get(node.parent) if node.parent else None
        if old is not None:
            old.children.discard(node.key)
        node.parent = parent.key
        parent.children.add(node.key)
        self._redepth(tree, node, parent.depth + 1)
        return self._slot_reply(tree, node)

    def _gc(self) -> None:
        ttl = CONFIG.bcast_tree_ttl_s
        now = time.monotonic()
        for oid in [oid for oid, t in self.trees.items()
                    if now - t.last_touch > ttl]:
            self.trees.pop(oid, None)


# ---------------------------------------------------------------------------
# agent-side: consumer loop
# ---------------------------------------------------------------------------
async def bcast_fetch(agent, hex_id: str, size: int, holders: List[Dict],
                      progress: TransferProgress) -> str:
    """Tree-coordinated pull of one object into the local store.

    Returns 'ok' (sealed locally) or 'fallback' (head unreachable, tree
    drained, or re-parent budget exhausted — the caller runs the plain
    striped pull, keeping ``progress`` registered so children of this
    node keep relaying either way).
    """
    pulls = agent.pulls
    my_addr = {"host": "127.0.0.1", "port": agent.tcp_port}
    timeout = CONFIG.control_rpc_timeout_s
    dead_parent: Optional[Dict] = None
    for _ in range(max(1, CONFIG.bcast_max_reparents) + 1):
        try:
            if dead_parent is None:
                reply = await agent.head.call(
                    "BcastJoin",
                    {"object_id": hex_id, "size": size, "addr": my_addr,
                     "roots": holders}, timeout=timeout)
            else:
                reply = await agent.head.call(
                    "BcastReparent",
                    {"object_id": hex_id, "addr": my_addr,
                     "dead": dead_parent}, timeout=timeout)
        except asyncio.CancelledError:
            raise
        except Exception:
            pulls.bcast_fallbacks += 1
            return "fallback"
        if not reply or reply.get("fallback"):
            pulls.bcast_fallbacks += 1
            return "fallback"
        parent = reply["parent"]
        pulls.bcast_joins += 1
        pulls.bcast_last_depth = int(reply.get("depth", 0))
        status = await pulls.fetch(
            hex_id, [parent], meta=(size, [parent], False),
            progress=progress)
        if status == "ok":
            pulls.bcast_tree_pulls += 1
            # (the parent is already recorded as a remote-tier restore
            # source by PullManager.fetch's ok path)
            try:
                await agent.head.call(
                    "BcastReady", {"object_id": hex_id, "addr": my_addr},
                    timeout=timeout)
            except Exception:
                pass  # advisory; the tree converges without it
            return "ok"
        if status == "local":
            return "fallback"
        # 'conn' (parent dead / unreachable) or 'absent' (parent gave up
        # or evicted mid-relay): report it dead and take a new slot
        pulls.bcast_reparents_client += 1
        dead_parent = parent
    pulls.bcast_fallbacks += 1
    return "fallback"
