"""Bin-packing of unfulfilled resource demands onto node types
(reference: python/ray/autoscaler/_private/resource_demand_scheduler.py).

Given the live cluster view and a list of pending resource requests, decide
how many nodes of each type to launch. First-fit-decreasing over demands,
respecting per-type max_workers and the global max.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ray_tpu._private.resources import ResourceSet


def _fit_on(demand: ResourceSet, pools: List[ResourceSet]) -> bool:
    """Try to place `demand` on one of `pools` (mutating the winner)."""
    for pool in pools:
        if demand.fits(pool):
            pool.subtract(demand)
            return True
    return False


def get_nodes_to_launch(
    node_types: Dict[str, Dict],
    demands: List[Dict[str, int]],
    existing_available: List[Dict[str, int]],
    existing_counts: Dict[str, int],
    max_workers: int,
    total_workers: int,
) -> Dict[str, int]:
    """Returns {node_type: count} to launch.

    node_types: {name: {"resources": {...}, "max_workers": int}}
    demands: wire-format ResourceSets of queued lease requests
    existing_available: wire-format available pools of alive nodes
    existing_counts: current worker count per type
    """
    pools = [ResourceSet.from_wire(w) for w in existing_available]
    unfulfilled: List[ResourceSet] = []
    for wire in demands:
        demand = ResourceSet.from_wire(wire)
        if not _fit_on(demand, pools):
            unfulfilled.append(demand)
    if not unfulfilled:
        return {}

    # largest demands first so big requests claim fresh nodes before small
    # ones fragment them
    unfulfilled.sort(key=lambda r: -sum(r.to_wire().values()))

    to_launch: Dict[str, int] = {}
    counts = dict(existing_counts)
    budget = max(0, max_workers - total_workers)
    new_pools: List[ResourceSet] = []
    for demand in unfulfilled:
        if _fit_on(demand, new_pools):
            continue
        chosen = None
        for name, spec in node_types.items():
            cap = ResourceSet(dict(spec.get("resources", {})))
            if not demand.feasible_on(cap):
                continue
            if counts.get(name, 0) >= spec.get("max_workers", max_workers):
                continue
            chosen = (name, cap)
            break
        if chosen is None or budget <= 0:
            continue  # infeasible or at capacity: demand stays pending
        name, cap = chosen
        cap.subtract(demand)
        new_pools.append(cap)
        to_launch[name] = to_launch.get(name, 0) + 1
        counts[name] = counts.get(name, 0) + 1
        budget -= 1
    return to_launch
