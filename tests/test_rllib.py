"""RLlib tests (reference analog: rllib/tests + tuned_examples learning
checks — CartPole PPO must actually learn, SURVEY §4 tier 4)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPO, PPOConfig, RLModuleSpec
from ray_tpu.rllib.core.learner import PPOLearner
from ray_tpu.rllib.utils.gae import compute_gae


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


# --------------------------------------------------------------- unit tests
def test_gae_matches_manual():
    # single env, 3 steps, no dones
    rewards = np.array([[1.0], [1.0], [1.0]], np.float32)
    values = np.array([[0.5], [0.5], [0.5]], np.float32)
    dones = np.zeros((3, 1), np.float32)
    last_v = np.array([0.5], np.float32)
    adv, vt = compute_gae(rewards, values, dones, last_v,
                          gamma=0.9, lam=1.0)
    # delta_t = 1 + 0.9*0.5 - 0.5 = 0.95; lam=1 => discounted sums
    assert adv[2, 0] == pytest.approx(0.95)
    assert adv[1, 0] == pytest.approx(0.95 + 0.9 * 0.95)
    assert vt[0, 0] == pytest.approx(adv[0, 0] + 0.5)


def test_gae_cuts_at_done():
    rewards = np.ones((4, 1), np.float32)
    values = np.zeros((4, 1), np.float32)
    dones = np.array([[0.0], [1.0], [0.0], [0.0]], np.float32)
    adv, _ = compute_gae(rewards, values, dones, np.zeros(1, np.float32),
                         gamma=0.9, lam=1.0)
    # step 1 terminates: its advantage is just its reward
    assert adv[1, 0] == pytest.approx(1.0)
    # step 0 bootstraps from step 1 value but recursion restarts after done
    assert adv[0, 0] == pytest.approx(1.0 + 0.9 * 1.0)


def test_ppo_learner_moves_policy_toward_advantage():
    spec = RLModuleSpec(obs_dim=3, action_dim=2)
    lrn = PPOLearner(spec, {"lr": 0.01, "num_epochs": 10,
                            "minibatch_size": 128})
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(128, 3)).astype(np.float32)
    # action 0 has positive advantage, action 1 negative (advantages are
    # standardized per minibatch, so they must vary to carry signal)
    actions = (np.arange(128) % 2).astype(np.int64)
    adv = np.where(actions == 0, 1.0, -1.0).astype(np.float32)
    out0 = lrn.module.forward(lrn.params, obs)
    batch = {"obs": obs, "actions": actions,
             "logp": np.asarray(lrn.module.dist.logp(
                 out0["logits"], actions)),
             "advantages": adv,
             "value_targets": np.zeros(128, np.float32)}
    zeros = np.zeros(128, np.int64)
    p0 = float(np.mean(np.exp(lrn.module.dist.logp(
        out0["logits"], zeros))))
    lrn.update(batch)
    out1 = lrn.module.forward(lrn.params, obs)
    p1 = float(np.mean(np.exp(lrn.module.dist.logp(
        out1["logits"], zeros))))
    assert p1 > p0, f"policy did not move toward advantage: {p0} -> {p1}"


def test_config_fluent_and_build(ray4):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .training(lr=1e-3, train_batch_size=64, minibatch_size=32,
                     num_epochs=1, clip_param=0.3)
           .debugging(seed=7))
    assert cfg.clip_param == 0.3
    algo = cfg.build()
    try:
        result = algo.train()
        assert result["env_steps_this_iter"] >= 64
        assert "total_loss" in result
        assert result["training_iteration"] == 1
    finally:
        algo.stop()

    with pytest.raises(ValueError):
        PPOConfig().framework("torch")


def test_ppo_learns_cartpole(ray4):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                        rollout_fragment_length=64)
           .training(lr=3e-4, train_batch_size=2048, minibatch_size=256,
                     num_epochs=6, entropy_coeff=0.01)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        best = -np.inf
        for i in range(40):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 150.0:
                break
        assert best >= 150.0, f"PPO failed to learn CartPole: best={best}"
        # inference helper: greedy action is valid
        act = algo.compute_single_action(np.zeros(4, np.float32))
        assert act in (0, 1)
    finally:
        algo.stop()


def test_checkpoint_restore(ray4, tmp_path):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .training(train_batch_size=32, minibatch_size=32, num_epochs=1))
    algo = cfg.build()
    try:
        algo.train()
        d = str(tmp_path / "ckpt")
        import os

        os.makedirs(d, exist_ok=True)
        algo.save_checkpoint(d)
        w0 = algo.get_weights()
    finally:
        algo.stop()

    algo2 = cfg.copy().build()
    try:
        algo2.load_checkpoint(d)
        w1 = algo2.get_weights()
        np.testing.assert_allclose(
            np.asarray(w0["pi"][0]["w"]), np.asarray(w1["pi"][0]["w"]))
    finally:
        algo2.stop()


def test_env_runner_fault_tolerance(ray4):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                        rollout_fragment_length=16)
           .training(train_batch_size=64, minibatch_size=32, num_epochs=1))
    algo = cfg.build()
    try:
        algo.train()
        # kill one runner; the next step must replace it and continue
        ray_tpu.kill(algo.env_runners[0])
        result = algo.train()
        assert result["env_steps_this_iter"] >= 32
        result = algo.train()
        assert result["env_steps_this_iter"] >= 64
    finally:
        algo.stop()
