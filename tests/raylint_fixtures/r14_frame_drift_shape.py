"""R14 regression fixture: msgpack wire-frame contract drift.

The shipped shape (PR 11/18): the mux/shm/batch framing contracts —
single-letter keys like ``"s"``/``"q"``/``"ai"`` riding
``PushTaskBatchStream`` — hold by convention only; a typo'd key on one
of several send sites ships silently and surfaces as a hang three
modules away. R14 joins send-path dict literals with the registered
handler's payload reads, per RPC method name.

Shapes below (one contract per method name):

- ``UpdateLoad`` — the send-only wire key: ``"hint"`` is built into the
  frame but the handler never reads it (dead bytes / silently-ignored
  feature). The second send site omitting the optional ``"load"`` key
  is fine by design.
- ``FetchStatus`` — the read-but-never-sent key: the handler defaults
  ``"deadline_ms"`` but no literal send site ever ships it, so the read
  can only see the default.
- ``PushSpans`` — the type-incoherent key: ``"seq"`` is sent as int on
  one path and str on another; the handler can rely on neither.
- ``ForwardBlob`` — opaque handler (payload forwarded wholesale):
  send-only checking is disabled, no flag.
- ``ListNodes`` — a ``**``-expanded send site: read-never-sent is
  suppressed because not every send site is a full literal, no flag.

The ``reg = ..._server.add_handler`` alias mirrors the registration
idiom gcs.py/agent.py actually use.
"""


class RpcServerShape:
    def add_handler(self, name, fn):
        pass


class RpcClientShape:
    async def call(self, method, payload):
        pass

    def push(self, method, payload):
        pass


class AgentShape:
    def __init__(self, server, client, sink):
        self._server = server
        self._client = client
        self._sink = sink
        self._server.add_handler("UpdateLoad", self._handle_update_load)
        reg = self._server.add_handler
        reg("FetchStatus", self._handle_fetch_status)
        reg("PushSpans", self._handle_push_spans)
        self._server.add_handler("ForwardBlob", self._handle_forward_blob)
        self._server.add_handler("ListNodes", self._handle_list_nodes)

    # -- receive side ---------------------------------------------------
    def _handle_update_load(self, conn, payload):
        return payload["node_id"], payload.get("load")

    def _handle_fetch_status(self, conn, payload):
        return payload["verbose"], payload.get("deadline_ms")  # expect-R14

    def _handle_push_spans(self, conn, payload):
        return payload["seq"], payload["spans"]

    def _handle_forward_blob(self, conn, payload):
        self._sink(payload)

    def _handle_list_nodes(self, conn, payload):
        return payload.get("page_token")

    # -- send side ------------------------------------------------------
    async def report(self):
        await self._client.call("UpdateLoad", {
            "node_id": "n1",
            "load": 0.5,
            "hint": "idle",  # expect-R14
        })

    async def report_minimal(self):
        # omitting the optional "load" key is fine by design
        await self._client.call("UpdateLoad", {"node_id": "n2"})

    async def fetch(self):
        return await self._client.call("FetchStatus", {"verbose": True})

    def push_spans(self, spans):
        self._client.push("PushSpans", {"seq": 1, "spans": spans})

    def push_spans_retry(self, spans):
        self._client.push("PushSpans", {"seq": "r1", "spans": spans})  # expect-R14

    async def forward(self, blob):
        await self._client.call("ForwardBlob", {"anything": blob})

    async def list_nodes(self, extra):
        return await self._client.call("ListNodes", {**extra})
