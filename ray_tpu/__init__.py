"""ray_tpu — a TPU-native distributed compute framework.

The capability surface of Ray (tasks, actors, objects, placement groups, and
the Data/Train/Tune/Serve/RLlib libraries) re-designed TPU-first: JAX/XLA is
the compute substrate, device meshes + ICI collectives are the data plane, and
the distributed runtime orchestrates between meshes.

Public core API parity (reference: ``python/ray/__init__.py``):
``init/shutdown/is_initialized/remote/get/put/wait/kill/cancel/
get_actor/method/nodes/cluster_resources/available_resources/timeline``.
"""

from __future__ import annotations

import atexit
import inspect
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu import exceptions
from ray_tpu._private import worker as _worker_mod
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ray_tpu._private.node import Node
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.streaming import (
    DynamicObjectRefGenerator, ObjectRefGenerator)
from ray_tpu.actor import ActorClass, ActorHandle, method, exit_actor
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "exit_actor", "nodes",
    "cluster_resources", "available_resources", "ObjectRef", "ActorHandle",
    "get_runtime_context", "exceptions", "timeline", "__version__",
    "ObjectRefGenerator", "DynamicObjectRefGenerator",
]

_init_lock = threading.Lock()
_global_node: Optional[Node] = None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    labels: Optional[Dict[str, str]] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    _node: Optional[Node] = None,
    **_kwargs,
) -> "ClientContext":
    """Start (or connect to) a cluster and attach this process as a driver.

    - ``init()`` boots a local head + agent (reference: worker.py:1225).
    - ``init(address="host:port")`` connects to an existing head by starting a
      local agent joined to it.
    - ``init(_node=...)`` attaches to an already-running Node (tests).
    """
    global _global_node
    if address and address.startswith("ray://"):
        # client mode: proxy the API to a remote driver (reference:
        # client_builder.py ray.init("ray://...") path). Named params ride
        # along so e.g. namespace reaches the server-side driver.
        from ray_tpu.util.client import connect as _client_connect

        named = {"num_cpus": num_cpus, "num_tpus": num_tpus,
                 "resources": resources,
                 "object_store_memory": object_store_memory,
                 "labels": labels, "namespace": namespace}
        fwd = {k: v for k, v in named.items() if v is not None}
        fwd.update(_kwargs)
        return _client_connect(address, **fwd)
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return ClientContext(_worker_mod.global_worker)
            raise RuntimeError("ray_tpu.init() called twice")
        # stale-session GC: reclaim /dev/shm segments and session dirs
        # whose registered pids are all dead — a previous run's leak must
        # not starve this one (lifecycle supervisor contract)
        try:
            from ray_tpu._private import lifecycle as _lifecycle

            _lifecycle.gc_stale_sessions()
        except Exception:
            pass
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            res["TPU"] = float(num_tpus)
        if _node is not None:
            node = _node
        elif address is None or address == "local":
            node = Node(head=True, resources=res or None, labels=labels,
                        object_store_memory=object_store_memory)
            node.start()
            _global_node = node
        else:
            host, _, port = address.partition(":")
            node = Node(head=False, head_host=host or "127.0.0.1",
                        head_port=int(port), resources=res or None, labels=labels,
                        object_store_memory=object_store_memory)
            node.start()
            _global_node = node
        w = _worker_mod.Worker()
        w.namespace = namespace
        w.connect(node.agent_unix_path, mode=_worker_mod.Worker.MODE_DRIVER)
        atexit.register(shutdown)
        return ClientContext(w)


def shutdown() -> None:
    global _global_node
    w = _worker_mod.global_worker
    if w is not None:
        try:
            # acked flush: events/spans recorded right before shutdown
            # survive into the head ring instead of racing the disconnect
            w.flush_task_events(wait=True)
        except Exception:
            pass
        w.disconnect()
    if _global_node is not None:
        _global_node.stop(cleanup_session=True)
        _global_node = None
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass


def is_initialized() -> bool:
    return _worker_mod.global_worker is not None and _worker_mod.global_worker.connected


def _require_worker() -> _worker_mod.Worker:
    w = _worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


def remote(*args, **options):
    """``@ray_tpu.remote`` for functions and classes
    (reference: python/ray/_private/worker.py remote)."""
    if len(args) == 1 and not options and (inspect.isfunction(args[0]) or
                                           inspect.isclass(args[0])):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target)
        return RemoteFunction(target)

    def deco(target):
        if inspect.isclass(target):
            return ActorClass(target, **options)
        return RemoteFunction(target, **options)

    return deco


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    w = _require_worker()
    if isinstance(refs, ObjectRef):
        return w.get([refs], timeout=timeout)[0]
    # compiled-DAG result handles resolve through their channel, not the
    # object store (reference: CompiledDAGRef supports ray.get)
    compiled_get = getattr(refs, "_compiled_get", None)
    if compiled_get is not None:
        return compiled_get(timeout=timeout)
    if not isinstance(refs, (list, tuple)):
        raise TypeError("ray_tpu.get() expects an ObjectRef or a list of them")
    if refs and any(hasattr(r, "_compiled_get") for r in refs):
        if not all(hasattr(r, "_compiled_get") for r in refs):
            raise TypeError(
                "ray_tpu.get() cannot mix CompiledDAGRefs with ObjectRefs "
                "in one list")
        return [r._compiled_get(timeout=timeout) for r in refs]
    return w.get(list(refs), timeout=timeout)


def put(value: Any) -> ObjectRef:
    w = _require_worker()
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed")
    return w.put(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    w = _require_worker()
    refs = list(refs)
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return w.wait(refs, num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    w = _require_worker()
    w.kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    w = _require_worker()
    w.cancel_task(ref, force=force)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    w = _require_worker()
    actor_id, view = w.get_named_actor(name, namespace)
    return ActorHandle(actor_id, view.get("class_name", "Actor"))


def nodes() -> List[Dict]:
    w = _require_worker()
    return w._acall(w.head.call("ListNodes", {}, timeout=CONFIG.control_rpc_timeout_s))


def cluster_resources() -> Dict[str, float]:
    from ray_tpu._private.resources import ResourceSet

    total: Dict[str, float] = {}
    for n in nodes():
        if not n["alive"]:
            continue
        for k, v in ResourceSet.from_wire(n["resources_total"]).to_dict().items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    from ray_tpu._private.resources import ResourceSet

    total: Dict[str, float] = {}
    for n in nodes():
        if not n["alive"]:
            continue
        for k, v in ResourceSet.from_wire(n["resources_available"]).to_dict().items():
            total[k] = total.get(k, 0.0) + v
    return total


def timeline(filename: Optional[str] = None) -> List[Dict]:
    """Chrome-trace / Perfetto timeline (reference:
    python/ray/_private/state.py:924 ``ray.timeline``).

    Built from the cluster flight recorder (ISSUE 14): nested per-phase
    ``X`` slices — submit → lease-wait → exec (arg-resolve / return-put),
    put/pull/broadcast object slices, actor-call enqueue→exec — grouped
    into one lane per trace with ``M`` process metadata, plus instant
    markers for legacy task state transitions. Spans exist only when
    ``task_event_sample_rate`` > 0; the state-transition instants are
    always present.

    The flush is ACKED through the head before reading (read-your-writes
    — the old ``time.sleep(0.05)`` race is gone).
    """
    from ray_tpu._private.events import to_chrome_trace

    w = _require_worker()
    w.flush_task_events(wait=True)
    events = w._acall(w.head.call("ListTaskEvents", {"limit": 100000},
                                  timeout=CONFIG.control_rpc_timeout_s))
    spans = w._acall(w.head.call("ListSpans", {"limit": 100000},
                                 timeout=CONFIG.control_rpc_timeout_s))
    out = to_chrome_trace(spans, events)
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(out, f)
    return out


class ClientContext:
    def __init__(self, worker):
        self._worker = worker
        self.address_info = {
            "node_id": worker.node_id,
            "session_dir": getattr(_global_node, "session_dir", ""),
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        shutdown()

    def disconnect(self):
        shutdown()
