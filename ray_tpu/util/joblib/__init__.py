"""joblib backend (reference: python/ray/util/joblib/ — registers a
``ray`` parallel backend so ``joblib.Parallel(backend="ray")`` — and thus
scikit-learn's n_jobs machinery — fans out over the cluster).

Usage:
    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray"):
        Parallel(n_jobs=8)(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations


def register_ray() -> None:
    """Register the 'ray' joblib backend (no-op if joblib is absent)."""
    try:
        from joblib.parallel import register_parallel_backend
    except ImportError as e:
        raise ImportError(
            "joblib is required for the ray joblib backend") from e
    from ray_tpu.util.joblib.ray_backend import RayBackend

    register_parallel_backend("ray", RayBackend)


__all__ = ["register_ray"]
