"""Log monitor (reference: python/ray/_private/log_monitor.py, 588 LoC —
tails worker log files and publishes lines to drivers via GCS pubsub,
producing the familiar ``(worker)``-prefixed driver output).

Runs inside each node agent's event loop; tracks per-file offsets and
publishes only appended content to the ``logs:all`` channel.

Known deviation: lines are not routed per job (the reference filters by the
publishing worker's job). Workers here are leased across jobs, so in a
multi-driver session every driver sees every worker's output; disable with
``RAY_TPU_LOG_TO_DRIVER=0``.
"""

from __future__ import annotations

import asyncio
import glob
import os
from typing import Callable, Dict


class LogMonitor:
    MAX_LINES_PER_BATCH = 200

    def __init__(self, log_dir: str, node_id: str,
                 publish: Callable, period_s: float = 0.5):
        self.log_dir = log_dir
        self.node_id = node_id
        self._publish = publish  # async fn(channel, message)
        self.period_s = period_s
        self._offsets: Dict[str, int] = {}

    async def run(self) -> None:
        while True:
            try:
                await self.poll_once()
            except Exception:
                pass  # missing dirs / rotated files are routine
            await asyncio.sleep(self.period_s)

    async def poll_once(self) -> None:
        for path in glob.glob(os.path.join(self.log_dir, "worker-*.out")) + \
                glob.glob(os.path.join(self.log_dir, "worker-*.err")):
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(path, 0)
            if size <= off:
                if size < off:
                    self._offsets[path] = 0  # truncated/rotated
                continue
            with open(path, "rb") as f:
                f.seek(off)
                data = f.read(1 << 20)
            # only ship complete lines; partial tail stays for next poll
            last_nl = data.rfind(b"\n")
            if last_nl < 0:
                continue
            self._offsets[path] = off + last_nl + 1
            lines = data[:last_nl].decode("utf-8", "replace").splitlines()
            src = os.path.basename(path).rsplit(".", 1)[0]
            is_err = path.endswith(".err")
            keep = [ln for ln in lines if ln.strip()]
            for i in range(0, len(keep), self.MAX_LINES_PER_BATCH):
                # one Publish RPC per chunk, not per line
                await self._publish("logs:all", {
                    "src": src + (" stderr" if is_err else ""),
                    "node_id": self.node_id[:8],
                    "lines": keep[i:i + self.MAX_LINES_PER_BATCH],
                })
