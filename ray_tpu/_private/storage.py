"""Pluggable storage backends for checkpoint/experiment persistence.

The reference persists checkpoints through pyarrow filesystems resolved
from the storage path's scheme (reference:
python/ray/train/_internal/storage.py:99-111 — `_upload_to_fs_path`, fs
resolved via `pyarrow.fs.FileSystem.from_uri`). This build keeps the same
shape without the pyarrow dependency: a scheme -> StorageBackend registry,
object-store (flat-key) semantics, and an in-tree fake remote backend so
the multi-host upload/restore paths are *executed* in tests rather than
mocked (VERDICT r3 missing #2).

Layout contract (identical to the reference's):

    {storage_path}/{experiment_name}/{trial_name}/checkpoint_NNNNNN/...

Consumers never touch a remote URI with os.path — everything goes through
the backend API. `file://` (and bare paths) map to the local filesystem;
`mock://` is always the in-tree fake; `gs://`/`s3://` resolve to fsspec
when installed, or to the fake when RAY_TPU_FAKE_REMOTE_STORAGE=1 (tests),
or raise with a pointer to `register_storage_backend`.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

_SCHEME_RE = re.compile(r"^([a-z][a-z0-9+.-]*)://", re.IGNORECASE)


def parse_uri(path: str) -> Tuple[Optional[str], str]:
    """-> (scheme or None, rest). ``file:///x`` -> ("file", "/x")."""
    m = _SCHEME_RE.match(path)
    if not m:
        return None, path
    return m.group(1).lower(), path[m.end():]


def is_remote_uri(path: Optional[str]) -> bool:
    if not path:
        return False
    scheme, _ = parse_uri(path)
    return scheme is not None and scheme != "file"


def join_uri(base: str, *parts: str) -> str:
    scheme, rest = parse_uri(base)
    joined = "/".join([rest.rstrip("/")] + [p.strip("/") for p in parts if p])
    return f"{scheme}://{joined}" if scheme else joined


def local_path(path: str) -> str:
    """Strip a file:// scheme; error on remote URIs."""
    scheme, rest = parse_uri(path)
    if scheme is None:
        return path
    if scheme == "file":
        return rest
    raise ValueError(f"{path} is not a local path")


class StorageBackend:
    """Object-store-flavored filesystem ABC. URIs are passed whole
    (scheme included); directories are prefixes, not entities."""

    def upload_dir(self, local_dir: str, uri: str) -> None:
        raise NotImplementedError

    def download_dir(self, uri: str, local_dir: str) -> None:
        raise NotImplementedError

    def write_bytes(self, uri: str, data: bytes) -> None:
        raise NotImplementedError

    def read_bytes(self, uri: str) -> bytes:
        raise NotImplementedError

    def exists(self, uri: str) -> bool:
        raise NotImplementedError

    def listdir(self, uri: str) -> List[str]:
        """Immediate children names under the prefix."""
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        """Recursive delete of the prefix; idempotent."""
        raise NotImplementedError

    def makedirs(self, uri: str) -> None:
        """No-op for object stores; real mkdir for local."""


class LocalBackend(StorageBackend):
    def _p(self, uri: str) -> str:
        scheme, rest = parse_uri(uri)
        return rest if scheme == "file" else uri

    def upload_dir(self, local_dir: str, uri: str) -> None:
        dest = self._p(uri)
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)

    def download_dir(self, uri: str, local_dir: str) -> None:
        shutil.copytree(self._p(uri), local_dir, dirs_exist_ok=True)

    def write_bytes(self, uri: str, data: bytes) -> None:
        p = self._p(uri)
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def read_bytes(self, uri: str) -> bytes:
        with open(self._p(uri), "rb") as f:
            return f.read()

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._p(uri))

    def listdir(self, uri: str) -> List[str]:
        p = self._p(uri)
        return sorted(os.listdir(p)) if os.path.isdir(p) else []

    def delete(self, uri: str) -> None:
        p = self._p(uri)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.unlink(p)

    def makedirs(self, uri: str) -> None:
        os.makedirs(self._p(uri), exist_ok=True)


class FakeRemoteBackend(StorageBackend):
    """In-tree fake object store. Keys live as files under a shared root
    (cross-process: train workers upload, the driver restores) but callers
    only ever see URIs — exercising the exact code paths a real gs://
    bucket would, minus the network (VERDICT r3 weak: 'a checkpoint that
    lives on one host's disk is not fault tolerance' — this fake is the
    testable stand-in for the real backend registered on a pod).
    """

    def __init__(self, root: Optional[str] = None):
        self._root = root or os.environ.get(
            "RAY_TPU_FAKE_REMOTE_ROOT",
            os.path.join(tempfile.gettempdir(), "ray_tpu_fake_remote"))

    def _key(self, uri: str) -> str:
        scheme, rest = parse_uri(uri)
        if scheme is None:
            raise ValueError(f"fake remote backend needs a URI, got {uri}")
        return os.path.join(self._root, scheme, rest.strip("/"))

    def upload_dir(self, local_dir: str, uri: str) -> None:
        dest = self._key(uri)
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(local_dir, dest, dirs_exist_ok=True)

    def download_dir(self, uri: str, local_dir: str) -> None:
        src = self._key(uri)
        if not os.path.isdir(src):
            raise FileNotFoundError(f"no such remote prefix: {uri}")
        shutil.copytree(src, local_dir, dirs_exist_ok=True)

    def write_bytes(self, uri: str, data: bytes) -> None:
        p = self._key(uri)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def read_bytes(self, uri: str) -> bytes:
        try:
            with open(self._key(uri), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise FileNotFoundError(f"no such remote object: {uri}")

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._key(uri))

    def listdir(self, uri: str) -> List[str]:
        p = self._key(uri)
        return sorted(os.listdir(p)) if os.path.isdir(p) else []

    def delete(self, uri: str) -> None:
        p = self._key(uri)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.unlink(p)


class FsspecBackend(StorageBackend):
    """Real-cloud adapter: any scheme fsspec knows (gcsfs/s3fs must be
    installed — they are not in this image, so this is the documented
    production path, gated exactly like the reference gates pyarrow)."""

    def __init__(self, scheme: str):
        import fsspec  # raises ImportError when absent

        self._fs = fsspec.filesystem(scheme)

    def _p(self, uri: str) -> str:
        return parse_uri(uri)[1]

    def upload_dir(self, local_dir: str, uri: str) -> None:
        self._fs.put(local_dir.rstrip("/") + "/", self._p(uri), recursive=True)

    def download_dir(self, uri: str, local_dir: str) -> None:
        os.makedirs(local_dir, exist_ok=True)
        self._fs.get(self._p(uri).rstrip("/") + "/", local_dir,
                     recursive=True)

    def write_bytes(self, uri: str, data: bytes) -> None:
        with self._fs.open(self._p(uri), "wb") as f:
            f.write(data)

    def read_bytes(self, uri: str) -> bytes:
        with self._fs.open(self._p(uri), "rb") as f:
            return f.read()

    def exists(self, uri: str) -> bool:
        return self._fs.exists(self._p(uri))

    def listdir(self, uri: str) -> List[str]:
        base = self._p(uri).rstrip("/")
        return sorted(os.path.basename(p.rstrip("/"))
                      for p in self._fs.ls(base))

    def delete(self, uri: str) -> None:
        if self._fs.exists(self._p(uri)):
            self._fs.rm(self._p(uri), recursive=True)


_lock = threading.Lock()
_registry: Dict[str, StorageBackend] = {}


def register_storage_backend(scheme: str, backend: StorageBackend) -> None:
    with _lock:
        _registry[scheme.lower()] = backend


def get_storage_backend(path: str) -> StorageBackend:
    scheme, _ = parse_uri(path)
    if scheme in (None, "file"):
        return _get_or_create("file", lambda: LocalBackend())
    if scheme == "mock":
        return _get_or_create("mock", lambda: FakeRemoteBackend())
    with _lock:
        if scheme in _registry:
            return _registry[scheme]
    if os.environ.get("RAY_TPU_FAKE_REMOTE_STORAGE") == "1":
        return _get_or_create(scheme, lambda: FakeRemoteBackend())
    try:
        return _get_or_create(scheme, lambda: FsspecBackend(scheme))
    except (ImportError, ValueError) as e:
        # fsspec absent, or present but without this protocol's filesystem
        # (gcsfs/s3fs are separate packages)
        raise RuntimeError(
            f"no storage backend for {scheme}:// ({e}) — install fsspec + "
            f"the {scheme} filesystem, or register one with "
            "ray_tpu._private.storage.register_storage_backend"
        ) from None


def _get_or_create(scheme, factory) -> StorageBackend:
    with _lock:
        if scheme not in _registry:
            _registry[scheme] = factory()
        return _registry[scheme]
