"""Model multiplexing (reference: python/ray/serve/multiplex.py —
@serve.multiplexed LRU-loads models per model-id; the router steers
requests for the same id to replicas that already hold it. Serve-on-TPU's
LoRA-adapter pattern: one base model per replica, adapters multiplexed).
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import functools
import inspect
from typing import Any, Callable, Dict, Optional

_request_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def _set_request_model_id(model_id: str) -> None:
    _request_model_id.set(model_id)


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the current request
    (reference: serve.get_multiplexed_model_id)."""
    return _request_model_id.get()


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator over an async ``load_model(model_id)`` function/method;
    calling it returns the cached model, LRU-evicting beyond the cap."""

    def deco(fn):
        caches: Dict[Optional[int], "collections.OrderedDict"] = {}
        locks: Dict[Optional[int], asyncio.Lock] = {}

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                owner, model_id = args
                key = id(owner)
                call = functools.partial(fn, owner)
            else:
                (model_id,) = args
                key, call = None, fn
            cache = caches.setdefault(key, collections.OrderedDict())
            # fast path: cached models never wait behind a slow load
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            # per-model lock so only duplicate loads serialize
            lock = locks.setdefault((key, model_id), asyncio.Lock())
            async with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = call(model_id)
                if inspect.iscoroutine(model):
                    model = await model
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    # eviction drops the reference; models owning device
                    # memory should release it in __del__. The per-model
                    # lock is kept: popping it while a waiter holds it
                    # would let two coroutines load the same model at once
                    # (locks are tiny; distinct model ids bound their count)
                    cache.popitem(last=False)
                return model

        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
