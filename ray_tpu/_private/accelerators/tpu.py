"""TPU topology detection and pod-slice resource advertising.

Behavioral parity with the reference's TPU support (reference:
``python/ray/_private/accelerators/tpu.py:75-398``): chips are detected from
``/dev/accel*`` / ``/dev/vfio`` or env overrides; per-task chip visibility is
granted via ``TPU_VISIBLE_CHIPS`` (+ host-bounds vars); multi-host pod slices
advertise a ``{slice_name}: 1`` resource on every host plus a
``TPU-{pod_type}-head: 1`` resource on worker 0, so a driver can schedule one
task on the slice head and fan SPMD tasks out to every host of the slice.

TPU-first deviation: TPU is a *predefined* resource in the scheduler's
resource algebra (see ``ray_tpu/_private/resources.py``), not a custom
resource bolted on after the fact.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.accelerators.accelerator import AcceleratorManager

# Env-var inputs (same contract the reference reads before GCE/GKE metadata,
# which makes fake-TPU-topology tests trivial):
ENV_NUM_CHIPS = "RAY_TPU_NUM_CHIPS"            # override chip count
ENV_ACCEL_TYPE = "TPU_ACCELERATOR_TYPE"        # e.g. "v5litepod-16"
ENV_WORKER_ID = "TPU_WORKER_ID"                # host index within the slice
ENV_SLICE_NAME = "TPU_NAME"                    # slice/pod name
ENV_CHIPS_PER_HOST_BOUNDS = "TPU_CHIPS_PER_HOST_BOUNDS"
ENV_HOST_BOUNDS = "TPU_HOST_BOUNDS"
ENV_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"

VALID_CHIP_REQUESTS = (1, 2, 4, 8)


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return ENV_VISIBLE_CHIPS

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        if ENV_NUM_CHIPS in os.environ:
            return int(os.environ[ENV_NUM_CHIPS])
        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        try:
            vfio = glob.glob("/dev/vfio/[0-9]*")
            return len(vfio)
        except OSError:
            return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        accel_type = os.environ.get(ENV_ACCEL_TYPE)
        if accel_type:
            # "v5litepod-16" -> "TPU-V5LITEPOD"
            return "TPU-" + accel_type.split("-")[0].upper()
        return None

    @staticmethod
    def get_current_pod_type() -> Optional[str]:
        accel_type = os.environ.get(ENV_ACCEL_TYPE)
        return accel_type

    @staticmethod
    def get_current_pod_worker_count() -> Optional[int]:
        """Hosts in the current slice, derived from the accelerator type
        (e.g. v5litepod-16 => 16 chips / 4 chips-per-host = 4 hosts)."""
        pod_type = os.environ.get(ENV_ACCEL_TYPE)
        if not pod_type or "-" not in pod_type:
            return None
        try:
            total_chips = int(pod_type.rsplit("-", 1)[1])
        except ValueError:
            return None
        chips_per_host = TPUAcceleratorManager._chips_per_host()
        return max(1, total_chips // chips_per_host)

    @staticmethod
    def _chips_per_host() -> int:
        bounds = os.environ.get(ENV_CHIPS_PER_HOST_BOUNDS)
        if bounds:
            dims = [int(x) for x in bounds.split(",")]
            out = 1
            for d in dims:
                out *= d
            return out
        from ray_tpu._private.config import CONFIG

        return CONFIG.tpu_chips_per_host_default

    @staticmethod
    def chips_per_host_for_topology(topology: str) -> Optional[int]:
        """Chips per host for a named slice topology (e.g. "v5e-8" → 8,
        "v5p-16" → 4). Single-host v5e slices put all chips on one host;
        multi-host slices are 4 chips/host across generations
        (reference: tpu.py pod-type accounting, tpu.py:198-287)."""
        try:
            gen, total_s = topology.rsplit("-", 1)
            total = int(total_s)
        except ValueError:
            return None
        if gen.lower() in ("v5e", "v5litepod", "v6e") and total <= 8:
            return total
        return min(total, 4)

    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> Tuple[bool, Optional[str]]:
        if quantity != int(quantity):
            return False, "TPU request must be a whole number of chips"
        if int(quantity) not in VALID_CHIP_REQUESTS and int(quantity) % 4 != 0:
            return (
                False,
                f"TPU request must be one of {VALID_CHIP_REQUESTS} or a "
                "multiple of 4 (whole hosts)",
            )
        return True, None

    @staticmethod
    def set_visible_accelerator_ids(ids: List[int]) -> None:
        os.environ[ENV_VISIBLE_CHIPS] = ",".join(str(i) for i in ids)

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Pod-slice resources (reference: tpu.py:335-398): every host in a
        slice gets `{slice_name}: 1`; host 0 additionally gets
        `TPU-{pod_type}-head: 1` so drivers can target the slice head."""
        out: Dict[str, float] = {}
        slice_name = os.environ.get(ENV_SLICE_NAME)
        pod_type = os.environ.get(ENV_ACCEL_TYPE)
        if slice_name:
            out[slice_name] = 1.0
        worker_id = os.environ.get(ENV_WORKER_ID)
        if pod_type and worker_id is not None and int(worker_id) == 0:
            out[f"TPU-{pod_type}-head"] = 1.0
        accel_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        if accel_type:
            out[accel_type] = 1.0
        return out
