"""Pluggable backpressure policies + resource manager for the streaming
executor (VERDICT r2 item 9).

Reference: python/ray/data/_internal/execution/backpressure_policy/
backpressure_policy.py (the ABC consulted by the scheduling loop via
``can_add_input``), concurrency_cap_backpressure_policy.py,
streaming_output_backpressure_policy.py, and
execution/resource_manager.py (per-op memory accounting + global budget).

Here the policies replace the executor's two hardcoded caps: every
dispatch decision asks each policy ``can_dispatch(op_index)``; a policy
list lives on the DataContext so users can extend or reorder it.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Dict, List, Optional, Type

if TYPE_CHECKING:
    from ray_tpu.data._internal.executor import StreamingExecutor, Topology


class BackpressurePolicy:
    """One throttling rule. Policies are constructed per-execution with the
    topology and executor, and consulted on every dispatch attempt; a
    single False vetoes the dispatch."""

    def __init__(self, topology: "Topology", executor: "StreamingExecutor"):
        self.topology = topology
        self.executor = executor

    def can_dispatch(self, op_index: int) -> bool:
        return True


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """Bound concurrent tasks per operator. The cap comes from the
    operator itself (``max_concurrency``, set by the user via
    ``map_batches(concurrency=...)``) or the context default — moved here
    from the operators' own ``can_dispatch`` so the rule is uniform and
    overridable (reference: concurrency_cap_backpressure_policy.py)."""

    def __init__(self, topology, executor):
        super().__init__(topology, executor)
        from ray_tpu.data.context import DataContext

        self._default_cap = DataContext.get_current() \
            .max_tasks_in_flight_per_op

    def can_dispatch(self, op_index: int) -> bool:
        op = self.topology.ops[op_index]
        cap = getattr(op, "max_concurrency", None) or self._default_cap
        return op.num_active_tasks() < cap


class StreamingOutputBackpressurePolicy(BackpressurePolicy):
    """Bound the bundles buffered at each operator's output edge and at the
    consumer edge, so a slow consumer throttles the whole pipeline instead
    of the dataset accumulating in RAM (reference:
    streaming_output_backpressure_policy.py
    MAX_BLOCKS_IN_OP_OUTPUT_QUEUE / MAX_BLOCKS_IN_GENERATOR_BUFFER)."""

    def __init__(self, topology, executor):
        super().__init__(topology, executor)
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        self.per_op_buffer = ctx.per_op_buffer
        self.output_buffer = ctx.output_buffer

    def can_dispatch(self, op_index: int) -> bool:
        if self.executor.out.qsize() >= self.output_buffer:
            return False
        op = self.topology.ops[op_index]
        backlog = len(op.output_queue)
        for dst, _ in self.topology.edges.get(op_index, []):
            backlog += len(self.topology.ops[dst].input_queue)
        return backlog < self.per_op_buffer


class ResourceBudgetBackpressurePolicy(BackpressurePolicy):
    """Global memory budget over buffered block bytes (the ResourceManager
    below does the accounting). When the pipeline holds more than
    ``DataContext.execution_memory_limit`` bytes of queued blocks, only the
    most-downstream dispatchable operator may run — draining toward the
    consumer frees memory; letting upstream reads run would grow it
    (reference: resource_manager.py ReservationOpResourceAllocator's
    downstream-first eviction order)."""

    def __init__(self, topology, executor):
        super().__init__(topology, executor)
        self.manager = executor.resource_manager

    def can_dispatch(self, op_index: int) -> bool:
        if self.manager.budget_bytes <= 0:   # unlimited
            return True
        if self.manager.usage_bytes() < self.manager.budget_bytes:
            return True
        # over budget: permit only the most-downstream op that could run,
        # so progress (and memory release) is still possible — never a
        # full stall
        return op_index == self.manager.most_downstream_dispatchable()


DEFAULT_BACKPRESSURE_POLICIES: List[Type[BackpressurePolicy]] = [
    ConcurrencyCapBackpressurePolicy,
    StreamingOutputBackpressurePolicy,
    ResourceBudgetBackpressurePolicy,
]


class ResourceManager:
    """Tracks how many bytes of block payload each operator currently holds
    in its queues (input + output edges, by block metadata — payloads stay
    in the object store, reference: execution/resource_manager.py
    update_usages). Cheap to recompute per scheduling step: topologies are
    a handful of ops with bounded queues."""

    def __init__(self, topology: "Topology", budget_bytes: int):
        self.topology = topology
        self.budget_bytes = budget_bytes

    def op_usage_bytes(self, op_index: int) -> int:
        op = self.topology.ops[op_index]
        total = 0
        for q in (op.input_queue, op.output_queue):
            for bundle in q:
                meta = getattr(bundle, "meta", None)
                if meta is not None:
                    total += meta.size_bytes
        # bytes held outside the queues: the streaming shuffle's sealed
        # shard objects (ISSUE 12) — without this the budget policy was
        # blind to the exchange's working set
        total += op.extra_usage_bytes()
        return total

    def usage_bytes(self) -> int:
        return sum(self.op_usage_bytes(i)
                   for i in range(len(self.topology.ops)))

    def most_downstream_dispatchable(self) -> Optional[int]:
        for i in reversed(range(len(self.topology.ops))):
            if self.topology.ops[i].can_dispatch():
                return i
        return None

    def usage_report(self) -> Dict[str, int]:
        return {op.name: self.op_usage_bytes(i)
                for i, op in enumerate(self.topology.ops)}
