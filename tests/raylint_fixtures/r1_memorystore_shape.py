"""R1 regression fixture: the MemoryStore GC-reentrancy deadlock (PR 5).

The shipped bug: ``ObjectRef.__del__`` (fired by a GC pass, on whatever
thread happened to allocate) called ``ReferenceCounter.remove_local_ref``
which called ``MemoryStore.delete`` — which took the store's plain
``threading.Lock``. When the GC pass started while the *same* thread was
already inside another ``MemoryStore`` critical section, the non-reentrant
acquire deadlocked the whole driver. Three classes between the destructor
and the lock; no single-file review saw it.

The three classes below are that chain, minimized. R1 must flag the
``with self._lock:`` in ``MemoryStoreShape.delete`` (reachable from
``ObjectRefShape.__del__``) and must NOT flag the ``SafeStoreShape`` twin,
which uses the RLock fix that shipped.
"""

import threading


class MemoryStoreShape:
    """The store: plain Lock guarding its table (the bug)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}

    def delete(self, key):
        with self._lock:  # expect-R1
            self._table.pop(key, None)


class ReferenceCounterShape:
    """The middle hop: no locks of its own, just the call edge."""

    def __init__(self, store):
        self._store = store

    def remove_local_ref(self, key):
        self._store.delete(key)


class ObjectRefShape:
    """The GC root: a destructor that walks into the store."""

    def __init__(self, rc, key):
        self._rc = rc
        self._key = key

    def __del__(self):
        self._rc.remove_local_ref(self._key)


class SafeStoreShape:
    """The shipped fix: RLock — same reachability, reentrant, no flag."""

    def __init__(self):
        self._lock = threading.RLock()
        self._table = {}

    def drop(self, key):
        with self._lock:
            self._table.pop(key, None)


class SafeRefShape:
    def __init__(self, store, key):
        self._safe_store = store
        self._key = key

    def __del__(self):
        self._safe_store.drop(self._key)
