"""OpenTelemetry-optional tracing (reference:
python/ray/util/tracing/tracing_helper.py — lazy opentelemetry import at
:36-57, context inject/extract around task submit/execute).

``opentelemetry`` is not bundled; when absent every helper degrades to a
no-op so instrumented code never pays for the option. Spans also mirror
into the task-event timeline so ``ray_tpu.timeline()`` shows user spans
next to task lifecycles even without an OTel backend.
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Any, Dict, Iterator, Optional

_tracer = None
_checked = False


def trace_enabled() -> bool:
    return os.environ.get("RAY_TPU_TRACING_ENABLED", "0") == "1"


def get_tracer():
    """The opentelemetry tracer, or None when the SDK is unavailable."""
    global _tracer, _checked
    if _checked:
        return _tracer
    _checked = True
    if not trace_enabled():
        return None
    try:
        from opentelemetry import trace  # optional dependency

        _tracer = trace.get_tracer("ray_tpu")
    except ImportError:
        _tracer = None
    return _tracer


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None
         ) -> Iterator[None]:
    """Context manager: an OTel span when available, else a timeline event."""
    tracer = get_tracer()
    start = time.time()
    if tracer is not None:
        with tracer.start_as_current_span(name, attributes=attributes or {}):
            yield
        return
    try:
        yield
    finally:
        _mirror_to_timeline(name, start, time.time(), attributes)


def _mirror_to_timeline(name: str, start: float, end: float,
                        attributes: Optional[Dict]) -> None:
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not getattr(w, "connected", False):
        return
    key = f"span-{os.getpid()}-{start:.6f}"
    for state, ts in (("PENDING", start), ("FINISHED", end)):
        w.task_events.append({
            "task_id": key,
            "job_id": w.job_id.hex() if w.job_id else "",
            "name": f"span::{name}", "state": state, "type": 0,
            "time": ts, "node_id": w.node_id or "",
        })


def traced(name: Optional[str] = None):
    """Decorator form of ``span``."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name or fn.__qualname__):
                return fn(*args, **kwargs)

        return wrapper

    return deco
