from ray_tpu.rllib.offline.json_io import JsonReader, JsonWriter

__all__ = ["JsonReader", "JsonWriter"]
