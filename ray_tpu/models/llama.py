"""Llama-2/3-family decoder-only transformer, TPU-first.

Design notes (why this is not a torch translation):
- Pure functional: params are a pytree of ``jnp.ndarray``; the forward pass is
  a jit-friendly function of (params, tokens). No module objects, no state.
- Every parameter carries *logical axis names* (see ``llama_logical_axes``) so
  the same model runs 1-chip or on any (data, fsdp, seq, tensor) mesh purely
  by changing the rule table — GSPMD inserts the collectives.
- Layers are stacked into single arrays (num_layers leading dim) and scanned
  with ``jax.lax.scan``: one compiled layer body regardless of depth, which
  keeps XLA compile time flat and enables per-layer remat.
- Attention dispatches to ``ray_tpu.ops`` (Pallas flash attention on TPU,
  reference einsum path elsewhere; ring attention when the seq axis > 1).
- bfloat16 activations / fp32 params+optimizer by default: MXU-native.

Reference capability being replaced: Train users bring HF torch models
(reference: python/ray/train/huggingface/, release/air_examples/gptj_deepspeed
_finetuning); here the model is framework-native.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import constrain


def _ring_seq_attention(q, k, v):
    """Sequence-parallel exact attention: shard_map over the ambient mesh's
    ``seq`` axis; kv chunks ride the ICI ring (ops.ring_attention)."""
    from ray_tpu.ops.ring_attention import ring_attention
    from ray_tpu.parallel.sharding import compat_shard_map, logical_to_spec

    qs = logical_to_spec(("batch", "seq", "heads", "head_dim"))
    fn = compat_shard_map(
        partial(ring_attention, axis_name="seq", causal=True),
        in_specs=(qs, qs, qs), out_specs=qs, check_vma=False)
    return fn(q, k, v)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    mlp_hidden: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16      # activation dtype
    param_dtype: Any = jnp.float32
    remat: bool = True             # checkpoint each layer (HBM↔FLOPs trade)
    remat_policy: str = "dots"     # dots (save matmuls) | full (recompute all)
    attn_impl: str = "auto"        # auto | flash | reference | ring_seq
    loss_chunk: int = 0            # >0: lm-head CE in seq chunks of this size
    #   (peak logits memory B*chunk*V instead of B*S*V; the backward
    #    recomputes each chunk's logits under jax.checkpoint)

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        return LlamaConfig(vocab_size=vocab_size, hidden=128, mlp_hidden=352,
                           num_layers=2, num_heads=4, num_kv_heads=2,
                           head_dim=32, max_seq_len=256, remat=False)

    @staticmethod
    def debug_1l() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128, hidden=64, mlp_hidden=176,
                           num_layers=1, num_heads=2, num_kv_heads=1,
                           head_dim=32, max_seq_len=128, remat=False)

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate fwd+bwd FLOPs/token: 6*N, plus the attention
        quadratic term 12*L*H*D*S when ``seq_len`` is given."""
        flops = 6.0 * self.num_params()
        if seq_len is not None:
            flops += (12.0 * self.num_layers * self.num_heads
                      * self.head_dim * seq_len)
        return flops

    def flops_per_token_frozen(self, trainable_params: int,
                               seq_len: Optional[int] = None) -> float:
        """Frozen-base (LoRA) fwd+bwd FLOPs/token: the backward still
        propagates activation grads through every frozen layer (2N) but
        forms weight grads only for the adapters — 4N_base + 6N_adapters.
        Attention's quadratic term keeps its full factor (dQ/dK/dV are
        activation grads)."""
        flops = 4.0 * self.num_params() + 6.0 * trainable_params
        if seq_len is not None:
            flops += (12.0 * self.num_layers * self.num_heads
                      * self.head_dim * seq_len)
        return flops

    def num_params(self) -> int:
        h, m, v = self.hidden, self.mlp_hidden, self.vocab_size
        qkv = h * (self.num_heads + 2 * self.num_kv_heads) * self.head_dim
        o = self.num_heads * self.head_dim * h
        mlp = 3 * h * m
        per_layer = qkv + o + mlp + 2 * h
        return self.num_layers * per_layer + 2 * v * h + h


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """Low-rank adaptation of the projection weights (frozen base).

    The reference fine-tunes LLMs by wrapping HF models with peft
    (reference: release/air_examples/gptj_deepspeed_finetuning,
    release/release_tests.yaml LLM fine-tune gates); here LoRA is native:
    adapters are a separate pytree, the base never enters the optimizer, and
    the deltas are applied activation-side (two thin matmuls per projection —
    never materializing the full-rank update, so remat recompute stays cheap).
    """
    rank: int = 16
    alpha: float = 32.0
    targets: Tuple[str, ...] = ("wq", "wk", "wv", "wo",
                                "w_gate", "w_up", "w_down")
    param_dtype: Any = jnp.float32

    @property
    def scale(self) -> float:
        return self.alpha / self.rank

    def num_params(self, cfg: LlamaConfig) -> int:
        h, m, r = cfg.hidden, cfg.mlp_hidden, self.rank
        nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        per = {"wq": h * r + r * nh * hd, "wk": h * r + r * nkv * hd,
               "wv": h * r + r * nkv * hd, "wo": nh * hd * r + r * h,
               "w_gate": h * r + r * m, "w_up": h * r + r * m,
               "w_down": m * r + r * h}
        return cfg.num_layers * sum(per[t] for t in self.targets)


# (in_axes of A, out_axes of B) per adaptable projection; the A/B shapes are
# in_axes+(rank,) and (rank,)+out_axes with a leading num_layers dim.
_LORA_SHAPES = {
    "wq": (("embed",), ("heads", "head_dim")),
    "wk": (("embed",), ("kv_heads", "head_dim")),
    "wv": (("embed",), ("kv_heads", "head_dim")),
    "wo": (("heads", "head_dim"), ("embed",)),
    "w_gate": (("embed",), ("mlp",)),
    "w_up": (("embed",), ("mlp",)),
    "w_down": (("mlp",), ("embed",)),
}


def _lora_dims(cfg: LlamaConfig):
    return {"embed": (cfg.hidden,), "mlp": (cfg.mlp_hidden,),
            "heads": (cfg.num_heads,), "kv_heads": (cfg.num_kv_heads,),
            "head_dim": (cfg.head_dim,)}


def init_lora(cfg: LlamaConfig, lcfg: LoraConfig, key: jax.Array) -> Dict:
    """A ~ truncated-normal fan-in, B = 0 (the adapted model starts exactly
    at the base), stacked over layers for the scanned body."""
    dims = _lora_dims(cfg)
    L, r = cfg.num_layers, lcfg.rank
    out = {}
    keys = jax.random.split(key, len(lcfg.targets))
    for k, name in zip(keys, lcfg.targets):
        in_ax, out_ax = _LORA_SHAPES[name]
        in_shape = sum((dims[a] for a in in_ax), ())
        out_shape = sum((dims[a] for a in out_ax), ())
        fan_in = 1
        for d in in_shape:
            fan_in *= d
        a = (jax.random.truncated_normal(
            k, -2, 2, (L,) + in_shape + (r,), jnp.float32)
            * fan_in ** -0.5).astype(lcfg.param_dtype)
        b = jnp.zeros((L, r) + out_shape, lcfg.param_dtype)
        out[name] = {"a": a, "b": b}
    return {"layers": out}


def lora_logical_axes(cfg: LlamaConfig, lcfg: LoraConfig) -> Dict:
    """Rank dim stays unsharded (it is tiny); in/out dims shard like the
    base weight they adapt so the activation-side matmuls need no extra
    resharding."""
    out = {}
    for name in lcfg.targets:
        in_ax, out_ax = _LORA_SHAPES[name]
        out[name] = {"a": (None,) + in_ax + (None,),
                     "b": (None, None) + out_ax}
    return {"layers": out}


def merge_lora(params: Dict, lora: Dict, cfg: LlamaConfig,
               lcfg: LoraConfig) -> Dict:
    """Fold adapters into the base weights (for serving/export)."""
    merged = dict(params)
    layers = dict(params["layers"])
    for name, ab in lora["layers"].items():
        w = layers[name]
        a2 = ab["a"].reshape(cfg.num_layers, -1, lcfg.rank)
        b2 = ab["b"].reshape(cfg.num_layers, lcfg.rank, -1)
        delta = jnp.einsum("lir,lro->lio", a2.astype(jnp.float32),
                           b2.astype(jnp.float32)) * lcfg.scale
        layers[name] = (w.astype(jnp.float32)
                        + delta.reshape(w.shape)).astype(w.dtype)
    merged["layers"] = layers
    return merged


def llama_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Pytree (same structure as params) of logical-axis tuples."""
    layer = {
        "wq": ("embed", "heads", "head_dim"),
        "wk": ("embed", "kv_heads", "head_dim"),
        "wv": ("embed", "kv_heads", "head_dim"),
        "wo": ("heads", "head_dim", "embed"),
        "w_gate": ("embed", "mlp"),
        "w_up": ("embed", "mlp"),
        "w_down": ("mlp", "embed"),
        "attn_norm": ("norm",),
        "mlp_norm": ("norm",),
    }
    # scanned layers carry a leading 'layers' dim — replicated (None)
    layers = {k: (None,) + v for k, v in layer.items()}
    return {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_llama(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Initialize params (truncated-normal fan-in scaling, fp32)."""
    h, m = cfg.hidden, cfg.mlp_hidden
    nh, nkv, hd, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    ks = jax.random.split(key, 10)
    pd = cfg.param_dtype

    def norm_init(shape, k, fan_in):
        scale = fan_in ** -0.5
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32)
                * scale).astype(pd)

    layers = {
        "wq": norm_init((L, h, nh, hd), ks[0], h),
        "wk": norm_init((L, h, nkv, hd), ks[1], h),
        "wv": norm_init((L, h, nkv, hd), ks[2], h),
        "wo": norm_init((L, nh, hd, h), ks[3], nh * hd),
        "w_gate": norm_init((L, h, m), ks[4], h),
        "w_up": norm_init((L, h, m), ks[5], h),
        "w_down": norm_init((L, m, h), ks[6], m),
        "attn_norm": jnp.ones((L, h), pd),
        "mlp_norm": jnp.ones((L, h), pd),
    }
    return {
        "embed": norm_init((cfg.vocab_size, h), ks[7], 1.0),
        "layers": layers,
        "final_norm": jnp.ones((h,), pd),
        "lm_head": norm_init((h, cfg.vocab_size), ks[8], h),
    }


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * w.astype(jnp.float32)).astype(dt)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, S, H, D]; rotate pairs (d, d + D/2) — llama convention."""
    d = x.shape[-1]
    half = d // 2
    freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freq  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _layer(cfg: LlamaConfig, x: jax.Array, lp: Dict[str, jax.Array],
           positions: jax.Array, kv_cache=None,
           cache_index: Optional[jax.Array] = None,
           lora: Optional[Dict[str, Any]] = None, lora_scale: float = 0.0):
    """One transformer block. x: [B, S, H_model]."""
    dt = cfg.dtype

    def _ld(name, t_in, eq_a, eq_b):
        """Activation-side LoRA delta: (t_in @ A) @ B * scale, or 0."""
        if lora is None or name not in lora:
            return 0
        ab = lora[name]
        t = jnp.einsum(eq_a, t_in, ab["a"].astype(dt))
        return jnp.einsum(eq_b, t, ab["b"].astype(dt)) * lora_scale

    # --- attention ---
    h = _rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = (jnp.einsum("bsh,hnd->bsnd", h, lp["wq"].astype(dt))
         + _ld("wq", h, "bsh,hr->bsr", "bsr,rnd->bsnd"))
    k = (jnp.einsum("bsh,hnd->bsnd", h, lp["wk"].astype(dt))
         + _ld("wk", h, "bsh,hr->bsr", "bsr,rnd->bsnd"))
    v = (jnp.einsum("bsh,hnd->bsnd", h, lp["wv"].astype(dt))
         + _ld("wv", h, "bsh,hr->bsr", "bsr,rnd->bsnd"))
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    q = constrain(q, ("batch", "seq", "heads", None))
    k = constrain(k, ("batch", "seq", "kv_heads", None))
    new_cache = None
    if kv_cache is not None:
        ck, cv = kv_cache  # [B, max_S, nkv, d]
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, cache_index, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, cache_index, axis=1)
        k, v = ck, cv
        new_cache = (ck, cv)
        attn_out = attention(q, k, v, impl="reference", causal=True,
                             q_offset=cache_index)
    else:
        if cfg.attn_impl == "ring_seq":
            attn_out = _ring_seq_attention(q, k, v)
        else:
            attn_out = attention(q, k, v, impl=cfg.attn_impl, causal=True)
    attn_out = constrain(attn_out, ("batch", "seq", "heads", None))
    x = (x + jnp.einsum("bsnd,ndh->bsh", attn_out, lp["wo"].astype(dt))
         + _ld("wo", attn_out, "bsnd,ndr->bsr", "bsr,rh->bsh"))
    # --- mlp (SwiGLU) ---
    h = _rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    gate = (jnp.einsum("bsh,hm->bsm", h, lp["w_gate"].astype(dt))
            + _ld("w_gate", h, "bsh,hr->bsr", "bsr,rm->bsm"))
    up = (jnp.einsum("bsh,hm->bsm", h, lp["w_up"].astype(dt))
          + _ld("w_up", h, "bsh,hr->bsr", "bsr,rm->bsm"))
    act = constrain(jax.nn.silu(gate) * up, ("batch", "seq", "mlp"))
    x = (x + jnp.einsum("bsm,mh->bsh", act, lp["w_down"].astype(dt))
         + _ld("w_down", act, "bsm,mr->bsr", "bsr,rh->bsh"))
    x = constrain(x, ("batch", "seq", "embed"))
    return x, new_cache


def llama_decode(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    kv_caches,
    cache_index: jax.Array,
    *,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, list]:
    """Incremental decode: tokens [B, S] appended to the kv caches at
    ``cache_index`` → (logits [B, S, V] fp32, updated caches). Python loop
    over layers so each layer's cache updates functionally in place."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32) + cache_index, (B, S))
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    new_caches = []
    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        x, c = _layer(cfg, x, lp, positions, kv_caches[i], cache_index)
        new_caches.append(c)
    x = _rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"].astype(cfg.dtype))
    return logits.astype(jnp.float32), new_caches


def llama_hidden(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    lora: Optional[Dict[str, Any]] = None,
    lora_cfg: Optional[LoraConfig] = None,
) -> jax.Array:
    """tokens [B, S] int32 → final hidden states [B, S, H] (activation
    dtype, post final-norm). Layers run under ``lax.scan`` with optional
    per-layer remat; LoRA adapters (if given) scan alongside the base."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"))

    scale = lora_cfg.scale if lora_cfg is not None else 0.0

    def scan_fn(carry, xs):
        lp, lo = xs
        y, _ = _layer(cfg, carry, lp, positions, lora=lo, lora_scale=scale)
        return y, None

    lo_layers = lora["layers"] if lora is not None else None
    if cfg.remat:
        # "dots": keep matmul outputs, recompute elementwise — near-zero
        # extra MXU work for most of full remat's memory win. "full":
        # recompute everything (longest-context fallback). "mixed:K":
        # first K layers keep their matmul outputs, the rest recompute —
        # spends whatever HBM headroom full remat leaves on skipping
        # recompute FLOPs (each dots layer trades ~160 MB at 7B/B=1/S=2k
        # for one layer-forward less recompute per step).
        if cfg.remat_policy.startswith("mixed:"):
            k = int(cfg.remat_policy.split(":", 1)[1])
            n = cfg.num_layers
            k = max(0, min(k, n))
            dots_fn = jax.checkpoint(
                scan_fn,
                policy=jax.checkpoint_policies.
                dots_with_no_batch_dims_saveable)
            full_fn = jax.checkpoint(
                scan_fn, policy=jax.checkpoint_policies.nothing_saveable)
            head = jax.tree.map(lambda a: a[:k], params["layers"])
            tail = jax.tree.map(lambda a: a[k:], params["layers"])
            lo_head = (jax.tree.map(lambda a: a[:k], lo_layers)
                       if lo_layers is not None else {})
            lo_tail = (jax.tree.map(lambda a: a[k:], lo_layers)
                       if lo_layers is not None else {})
            x, _ = jax.lax.scan(dots_fn, x, (head, lo_head))
            x, _ = jax.lax.scan(full_fn, x, (tail, lo_tail))
            return _rms_norm(x, params["final_norm"], cfg.rms_eps)
        if cfg.remat_policy not in ("dots", "full"):
            raise ValueError(
                f"remat_policy {cfg.remat_policy!r}: expected "
                "'dots'|'full'|'mixed:K'")
        policy = (jax.checkpoint_policies.nothing_saveable
                  if cfg.remat_policy == "full"
                  else jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        scan_fn = jax.checkpoint(scan_fn, policy=policy)
    # broadcast None through the scan when no adapters: xs must be a pytree
    # of arrays, so substitute an empty dict
    x, _ = jax.lax.scan(scan_fn, x, (params["layers"], lo_layers or {}))
    return _rms_norm(x, params["final_norm"], cfg.rms_eps)


def llama_forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    lora: Optional[Dict[str, Any]] = None,
    lora_cfg: Optional[LoraConfig] = None,
) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, V] (fp32). For kv-cache decoding
    use ``llama_decode``."""
    x = llama_hidden(params, tokens, cfg, positions=positions,
                     lora=lora, lora_cfg=lora_cfg)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"].astype(cfg.dtype))
    return logits.astype(jnp.float32)


def _nll_from_logits(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """-log p(target) without gather/scatter: the target logit comes from
    an iota-compare + masked reduce, so the backward is softmax - onehot
    (pure elementwise). ``take_along_axis`` over a 32k vocab axis lowers
    to a TPU gather whose BACKWARD is a serialized scatter — profiling
    the 7B step showed that formulation burning ~27% of the whole step
    inside the loss (xplane while-loop at ~5% MXU efficiency)."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    vocab_ids = jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, logits.ndim - 1)
    target_logit = jnp.sum(
        jnp.where(vocab_ids == targets[..., None], logits, 0.0), axis=-1)
    return lse - target_logit


def _chunked_ce(x, lm_head, targets, mask, chunk, dtype):
    """Cross-entropy over seq chunks: logits for one chunk at a time, each
    chunk's logits recomputed in the backward (jax.checkpoint) so peak
    memory is B*chunk*V instead of B*S*V — the difference between a 7B
    model fitting one 16-GiB chip or not."""
    B, S, H = x.shape
    assert S % chunk == 0, f"seq {S} not divisible by loss_chunk {chunk}"
    n = S // chunk
    xc = jnp.moveaxis(x.reshape(B, n, chunk, H), 1, 0)
    tc = jnp.moveaxis(targets.reshape(B, n, chunk), 1, 0)
    mc = (jnp.moveaxis(mask.reshape(B, n, chunk), 1, 0)
          if mask is not None else jnp.ones_like(tc, jnp.float32))

    @jax.checkpoint
    def body(carry, inp):
        xi, ti, mi = inp
        logits = jnp.einsum("bch,hv->bcv", xi, lm_head.astype(dtype))
        nll = _nll_from_logits(logits, ti)
        tot, cnt = carry
        return (tot + jnp.sum(nll * mi), cnt + jnp.sum(mi)), None

    (tot, cnt), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (xc, tc, mc))
    return tot / jnp.maximum(cnt, 1.0)


def llama_loss(params: Dict[str, Any], batch: Dict[str, jax.Array],
               cfg: LlamaConfig, *,
               lora: Optional[Dict[str, Any]] = None,
               lora_cfg: Optional[LoraConfig] = None) -> jax.Array:
    """Next-token cross-entropy; batch = {tokens [B,S]} or {inputs, targets}."""
    if "targets" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
        mask = batch.get("mask")
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
        mask = None
    x = llama_hidden(params, inputs, cfg, lora=lora, lora_cfg=lora_cfg)
    if cfg.loss_chunk:
        return _chunked_ce(x, params["lm_head"], targets, mask,
                           cfg.loss_chunk, cfg.dtype)
    logits = jnp.einsum("bsh,hv->bsv", x, params["lm_head"].astype(cfg.dtype))
    nll = _nll_from_logits(logits, targets)
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def llama_lora_loss(base_params: Dict[str, Any], lora: Dict[str, Any],
                    batch: Dict[str, jax.Array], cfg: LlamaConfig,
                    lcfg: LoraConfig) -> jax.Array:
    """Loss as a function of the ADAPTERS only — the signature
    ``make_train_step`` wants for frozen-base fine-tuning: grads flow
    through the frozen layers into A/B but no base dW is ever formed."""
    return llama_loss(base_params, batch, cfg, lora=lora, lora_cfg=lcfg)
