"""Dashboard-lite (reference: dashboard/ — DashboardHead head.py:81 aiohttp
REST + per-node agents; the React client is out of scope, the REST surface
is here).

One actor serves JSON state endpoints + Prometheus metrics over the same
hand-rolled asyncio HTTP server style as the Serve proxy:

- ``GET /api/nodes|actors|tasks|placement_groups|jobs``
- ``GET /api/cluster_status`` — resource totals/availability
- ``GET /api/jobs/<id>/logs``
- ``GET /metrics`` — Prometheus text (reference: metrics agent)
- ``GET /healthz``
"""

from __future__ import annotations

import asyncio
import json
import urllib.parse
from typing import Optional, Tuple

import ray_tpu

DASHBOARD_NAME = "RAY_TPU_DASHBOARD"


class _BadRequest(Exception):
    """Client-input error on an API route -> HTTP 400."""


class DashboardActor:
    def __init__(self, port: int = 8265, host: str = "127.0.0.1"):
        self.port = port
        self.host = host
        self._server = None

    async def ready(self) -> int:
        if self._server is None:
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _handle_conn(self, reader, writer):
        try:
            line = await reader.readline()
            if not line:
                return
            try:
                method, target, _ = line.decode("latin1").strip().split(" ", 2)
            except ValueError:
                return
            content_length = 0
            while True:
                h = await reader.readline()
                if not h or h in (b"\r\n", b"\n"):
                    break
                name, _, value = h.decode("latin1").partition(":")
                if name.strip().lower() == "content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        pass
            body = (await reader.readexactly(content_length)
                    if content_length else b"")
            status, payload, ctype = await self._dispatch(
                method, target, body)
            writer.write(
                f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n".encode("latin1"))
            writer.write(payload)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, method: str, target: str,
                        body: bytes = b"") -> Tuple[str, bytes, str]:
        split = urllib.parse.urlsplit(target)
        path = split.path
        query = dict(urllib.parse.parse_qsl(split.query))
        try:
            if path in ("/", "/index.html"):
                from ray_tpu.dashboard.web import INDEX_HTML

                return ("200 OK", INDEX_HTML.encode(),
                        "text/html; charset=utf-8")
            if path == "/healthz":
                return "200 OK", b"success", "text/plain"
            if path == "/grafana/dashboards":
                from ray_tpu.dashboard.grafana import (
                    generate_core_dashboard, generate_tpu_dashboard)

                return ("200 OK", json.dumps({
                    "dashboards": [generate_core_dashboard(),
                                   generate_tpu_dashboard()]}).encode(),
                    "application/json")
            if path == "/metrics":
                from ray_tpu.util.metrics import prometheus_text

                text = await asyncio.to_thread(prometheus_text)
                return "200 OK", text.encode(), "text/plain"
            if path == "/api/serve/applications" and method == "PUT":
                # declarative deploy (reference: dashboard serve REST
                # PUT /api/serve/applications/ consuming ServeDeploySchema)
                config = json.loads(body or b"{}")
                await asyncio.to_thread(self._serve_deploy, config)
                return "200 OK", b"{}", "application/json"
            if path.startswith("/api/"):
                try:
                    data = await asyncio.to_thread(self._api, path, query)
                except _BadRequest as e:
                    return ("400 Bad Request",
                            json.dumps({"error": str(e)}).encode(),
                            "application/json")
                if data is None:
                    return ("404 Not Found", b'{"error": "not found"}',
                            "application/json")
                return ("200 OK", json.dumps(data, default=str).encode(),
                        "application/json")
            return "404 Not Found", b'{"error": "no route"}', \
                "application/json"
        except Exception as e:
            return ("500 Internal Server Error",
                    json.dumps({"error": repr(e)}).encode(),
                    "application/json")

    def _serve_deploy(self, config: dict) -> None:
        from ray_tpu import serve

        serve.run_config(config, _blocking=False)

    def _serve_status(self):
        from ray_tpu import serve

        try:
            ctrl = serve._controller()
            routes = ray_tpu.get(ctrl.get_routes.remote(), timeout=10)
        except Exception:
            return {"applications": {}, "proxies": {}}
        try:
            # per-node ingress map (reference: serve status proxies
            # section fed by ProxyStateManager)
            proxies = ray_tpu.get(ctrl.get_proxy_info.remote(), timeout=10)
        except Exception:
            proxies = {}
        return {"applications": {
            app: {**serve.status(app), "route_prefix": prefix,
                  "ingress": ingress}
            for prefix, (app, ingress) in routes.items()},
            "proxies": proxies}

    def _api(self, path: str, query=None):
        from ray_tpu.util import state as state_api

        query = query or {}
        parts = [p for p in path.split("/") if p][1:]  # drop "api"
        if parts[0] == "serve" and len(parts) > 1 \
                and parts[1] == "applications":
            return self._serve_status()
        if parts[0] == "data_stats":
            # per-dataset per-operator execution stats published by
            # drivers (Dataset._publish_stats; reference: the dashboard's
            # Ray Data tab fed by _internal/stats.py)
            import json as _json

            from ray_tpu.experimental.internal_kv import (
                _internal_kv_get, _internal_kv_list)

            out = []
            for key in sorted(_internal_kv_list(b"__data_stats__:"))[-50:]:
                val = _internal_kv_get(key)
                if val:
                    out.append({"dataset": key.decode().split(":", 1)[1],
                                **_json.loads(val)})
            return out
        if parts[0] == "nodes":
            return state_api.list_nodes()
        if parts[0] == "node_stats":
            return state_api.get_node_stats()
        if parts[0] == "events":
            return state_api.list_cluster_events(
                severity=query.get("severity"), label=query.get("label"))
        if parts[0] == "workers":
            return state_api.list_workers()
        if parts[0] == "objects":
            return state_api.list_objects()
        if parts[0] in ("profile", "jax_trace"):
            try:
                worker_id = query["worker_id"]
                duration = float(query.get("duration_s", 2.0))
            except (KeyError, ValueError) as e:
                raise _BadRequest(
                    "profile endpoints need ?worker_id=<id>"
                    "[&duration_s=<seconds>]") from e
            fn = (state_api.profile_worker if parts[0] == "profile"
                  else state_api.capture_jax_trace)
            return fn(worker_id, duration)
        if parts[0] == "actors":
            return state_api.list_actors()
        if parts[0] == "tasks":
            return state_api.list_tasks()
        if parts[0] == "placement_groups":
            return state_api.list_placement_groups()
        if parts[0] == "cluster_status":
            return {"total": ray_tpu.cluster_resources(),
                    "available": ray_tpu.available_resources()}
        if parts[0] == "jobs":
            from ray_tpu.job_submission import JobSubmissionClient

            client = JobSubmissionClient()
            if len(parts) == 1:
                return client.list_jobs()
            if len(parts) == 3 and parts[2] == "logs":
                return {"logs": client.get_job_logs(parts[1])}
            return client.get_job_info(parts[1])
        return None


def start_dashboard(port: int = 0, host: str = "127.0.0.1") -> int:
    """Start (or get) the dashboard actor; returns its bound port."""
    try:
        actor = ray_tpu.get_actor(DASHBOARD_NAME, namespace="_dashboard")
    except Exception:
        actor = ray_tpu.remote(DashboardActor).options(
            name=DASHBOARD_NAME, namespace="_dashboard",
            max_concurrency=16, num_cpus=0.1).remote(port=port, host=host)
    return ray_tpu.get(actor.ready.remote(), timeout=60)
