"""Serve ASGI ingress, streaming responses, deployment graph (reference:
python/ray/serve/api.py:170 @serve.ingress, _private/replica.py:471
streaming, deployment_graph_build.py + drivers.py DAGDriver; VERDICT r1
item 5)."""

import http.client
import json

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    serve.start(http_options={"port": 0})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _http(method, path, body=None, port=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path, body=body)
    resp = conn.getresponse()
    data = resp.read()
    status, headers = resp.status, dict(resp.getheaders())
    conn.close()
    return status, headers, data


# a minimal ASGI3 app (the protocol FastAPI speaks) — no framework needed
async def toy_asgi_app(scope, receive, send):
    assert scope["type"] == "http"
    body = b""
    while True:
        msg = await receive()
        body += msg.get("body", b"")
        if not msg.get("more_body"):
            break
    if scope["path"] == "/hello":
        payload = json.dumps({
            "method": scope["method"],
            "query": scope["query_string"].decode(),
            "got": body.decode(),
        }).encode()
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"application/json"),
                                (b"x-toy", b"1")]})
        await send({"type": "http.response.body", "body": payload})
    else:
        await send({"type": "http.response.start", "status": 404,
                    "headers": []})
        await send({"type": "http.response.body", "body": b"nope"})


def test_asgi_ingress_routes_and_status(serve_cluster):
    @serve.deployment
    @serve.ingress(toy_asgi_app)
    class AsgiApp:
        pass

    serve.run(AsgiApp.bind(), name="asgi", route_prefix="/asgi")
    port = serve.get_http_port()

    status, headers, data = _http(
        "POST", "/asgi/hello?x=1", body=b"ping", port=port)
    assert status == 200
    assert headers.get("X-Toy") == "1" or headers.get("x-toy") == "1"
    payload = json.loads(data)
    assert payload == {"method": "POST", "query": "x=1", "got": "ping"}

    status, _, data = _http("GET", "/asgi/missing", port=port)
    assert status == 404 and data == b"nope"
    serve.delete("asgi")


def test_response_object_controls_status_and_headers(serve_cluster):
    @serve.deployment
    def responder(request):
        return serve.Response({"made": "it"}, status_code=201,
                              headers={"X-Custom": "yes"})

    serve.run(responder.bind(), name="resp", route_prefix="/resp")
    port = serve.get_http_port()
    status, headers, data = _http("GET", "/resp", port=port)
    assert status == 201
    assert headers.get("X-Custom") == "yes"
    assert json.loads(data) == {"made": "it"}
    serve.delete("resp")


def test_streaming_generator_endpoint_chunked(serve_cluster):
    @serve.deployment
    def stream_numbers(request):
        # declared below as generator: this wrapper exists to show plain
        # functions still work; actual streamer:
        raise AssertionError("unused")

    @serve.deployment
    def streamer(request):
        yield "first|"
        yield "second|"
        yield "third"

    serve.run(streamer.bind(), name="stream", route_prefix="/stream")
    port = serve.get_http_port()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", "/stream")
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Transfer-Encoding") == "chunked"
    data = resp.read()
    conn.close()
    assert data == b"first|second|third"
    serve.delete("stream")


def test_streaming_response_object(serve_cluster):
    @serve.deployment
    def eventsource(request):
        def gen():
            for i in range(3):
                yield f"data: {i}\n\n"
        return serve.StreamingResponse(gen(), media_type="text/event-stream")

    serve.run(eventsource.bind(), name="sse", route_prefix="/sse")
    port = serve.get_http_port()
    status, headers, data = _http("GET", "/sse", port=port)
    assert status == 200
    assert data == b"data: 0\n\ndata: 1\n\ndata: 2\n\n"
    serve.delete("sse")


def test_handle_level_streaming(serve_cluster):
    @serve.deployment
    class Tokens:
        def generate(self, n):
            for i in range(int(n)):
                yield f"tok{i}"

    serve.run(Tokens.bind(), name="tok", route_prefix="/tok")
    handle = serve.get_app_handle("tok")
    gen = handle.options(method_name="generate", stream=True).remote(4)
    assert list(gen) == ["tok0", "tok1", "tok2", "tok3"]
    serve.delete("tok")


def test_deployment_graph_dagdriver(serve_cluster):
    @serve.deployment
    class Adder:
        def __init__(self, delta):
            self.delta = delta

        def add(self, x):
            return x + self.delta

    @serve.deployment
    class Combiner:
        def combine(self, a, b):
            return {"sum": a + b}

    with serve.InputNode() as inp:
        a1 = Adder.bind(1)
        a2 = Adder.options(name="Adder2").bind(100)
        graph = Combiner.bind().combine.bind(
            a1.add.bind(inp), a2.add.bind(inp))

    serve.run(serve.DAGDriver.bind(graph,
                                   http_adapter=serve.json_request),
              name="graph", route_prefix="/graph")
    port = serve.get_http_port()
    status, _, data = _http("POST", "/graph", body=b"5", port=port)
    assert status == 200
    assert json.loads(data) == {"sum": 111}  # (5+1) + (5+100)

    # direct handle execution through the driver
    handle = serve.get_app_handle("graph")
    assert handle.options(method_name="predict").remote(7).result(60) == \
        {"sum": 115}
    serve.delete("graph")


def test_graph_applications_inside_containers(serve_cluster):
    """Applications nested in a list arg must still be deployed (walk()
    descends containers the same way graph build does)."""
    @serve.deployment
    class Member:
        def __init__(self, v):
            self.v = v

        def get(self, _x):
            return self.v

    @serve.deployment
    class Ensemble:
        def __init__(self, members):
            self.members = members

        def vote(self, x):
            return sum(m.get.remote(x).result(30) for m in self.members)

    m1 = Member.bind(10)
    m2 = Member.options(name="Member2").bind(32)
    serve.run(Ensemble.bind([m1, m2]), name="ens", route_prefix="/ens")
    handle = serve.get_app_handle("ens")
    assert handle.options(method_name="vote").remote(0).result(60) == 42
    serve.delete("ens")


def test_streaming_failure_truncates_chunked_body(serve_cluster):
    @serve.deployment
    def broken(request):
        yield "good|"
        raise RuntimeError("mid-stream boom")

    serve.run(broken.bind(), name="broken", route_prefix="/broken")
    port = serve.get_http_port()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", "/broken")
    resp = conn.getresponse()
    assert resp.getheader("Transfer-Encoding") == "chunked"
    # the error must NOT look like a clean end-of-response: the connection
    # closes without the chunked terminator
    with pytest.raises(http.client.IncompleteRead) as exc_info:
        resp.read()
    assert b"good|" in (exc_info.value.partial or b"")
    conn.close()
    serve.delete("broken")


def test_fastapi_route_rebinding_offline():
    """The FastAPI class-based-view mechanic (reference:
    _private/http_util.py make_fastapi_class_based_view): endpoints
    captured unbound at decoration time are rebound to the replica
    instance — verified against a minimal fastapi-shaped route table
    (fastapi itself is not in this image)."""
    from ray_tpu.serve.asgi import _bind_fastapi_routes

    class Route:
        def __init__(self, endpoint):
            self.endpoint = endpoint
            self.dependant = type("D", (), {"call": endpoint})()

    class App:
        def __init__(self, routes):
            self.routes = routes

    class Ingress:
        def __init__(self, tag):
            self.tag = tag

        def handler(self):
            return self.tag

    app = App([Route(Ingress.handler)])
    inst = Ingress("replica-7")
    _bind_fastapi_routes(app, inst)
    assert app.routes[0].endpoint() == "replica-7"      # bound method now
    assert app.routes[0].dependant.call() == "replica-7"
