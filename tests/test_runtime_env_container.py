"""Container runtime-env tests (VERDICT r2 item 7; reference:
python/ray/_private/runtime_env/container.py). Command construction is
tested offline (pure function); the e2e worker-in-container test skips
when no engine is installed, the reference's skip-if-no-podman pattern."""

import shutil

import pytest

from ray_tpu.runtime_env.container import (
    build_container_command, validate_container_spec,
    worker_container_command)
from ray_tpu.runtime_env.runtime_env import RuntimeEnv


HAVE_ENGINE = bool(shutil.which("podman") or shutil.which("docker"))


class TestSpecValidation:
    def test_image_required(self):
        with pytest.raises(ValueError, match="image"):
            validate_container_spec({})

    def test_run_options_typed(self):
        with pytest.raises(TypeError, match="run_options"):
            validate_container_spec({"image": "x", "run_options": "oops"})

    def test_runtime_env_accepts_container_field(self):
        env = RuntimeEnv(container={"image": "python:3.12-slim"})
        assert env["container"]["image"] == "python:3.12-slim"

    def test_runtime_env_rejects_bad_container(self):
        with pytest.raises(ValueError):
            RuntimeEnv(container={"no_image": True})


class TestCommandShape:
    SPEC = {"image": "raytpu-worker:dev",
            "run_options": ["--cap-drop", "ALL"]}

    def test_basic_shape(self):
        cmd = build_container_command(
            self.SPEC, ["python", "-m", "w"],
            mounts=["/tmp/session"], env={"A": "1"}, engine="docker")
        assert cmd[:3] == ["docker", "run", "--rm"]
        assert "--network=host" in cmd and "--ipc=host" in cmd
        i = cmd.index("-v")
        assert cmd[i + 1] == "/tmp/session:/tmp/session"
        e = cmd.index("-e")
        assert cmd[e + 1] == "A=1"
        # run_options come right before the image; inner command after
        img = cmd.index("raytpu-worker:dev")
        assert cmd[img - 2:img] == ["--cap-drop", "ALL"]
        assert cmd[img + 1:] == ["python", "-m", "w"]

    def test_duplicate_mounts_collapse(self):
        cmd = build_container_command(
            self.SPEC, ["w"], mounts=["/s", "/s"], env={}, engine="podman")
        assert cmd.count("/s:/s") == 1

    def test_worker_command_mounts_package_and_dirs(self, tmp_path):
        cmd = worker_container_command(
            self.SPEC, str(tmp_path / "sess"), str(tmp_path / "store"),
            {"RAY_TPU_WORKER_ID": "abc"}, engine="docker")
        joined = " ".join(cmd)
        assert f"{tmp_path}/sess:{tmp_path}/sess" in joined
        assert f"{tmp_path}/store:{tmp_path}/store" in joined
        # the ray_tpu package parent rides along with PYTHONPATH set
        import ray_tpu, os

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        assert f"{pkg_parent}:{pkg_parent}" in joined
        assert any(a.startswith("PYTHONPATH=") and pkg_parent in a
                   for a in cmd)
        assert any(a == "RAY_TPU_WORKER_ID=abc" for a in cmd)
        assert cmd[-3:] == ["python", "-m",
                            "ray_tpu._private.worker_process"]

    def test_no_engine_raises_setup_error(self, tmp_path, monkeypatch):
        from ray_tpu.runtime_env.runtime_env import RuntimeEnvSetupError

        monkeypatch.setattr(shutil, "which", lambda *_: None)
        with pytest.raises(RuntimeEnvSetupError, match="podman nor docker"):
            worker_container_command(
                {"image": "x"}, str(tmp_path), str(tmp_path), {})


class TestPoolAffinity:
    def test_container_lease_never_takes_pristine_worker(self):
        """agent._pop_idle_worker(tagged_only=True) must skip env_key=None
        workers — a host process cannot retroactively enter an image."""
        import ray_tpu._private.agent as agent_mod

        class FakeProc:
            def poll(self):
                return None  # still running

        class FakeAgent:
            _pop_idle_worker = agent_mod.NodeAgent._pop_idle_worker

        a = FakeAgent()
        pristine = agent_mod.WorkerHandle("w1", proc=FakeProc())
        pristine.registered.set()
        a.idle_workers = [pristine]
        assert a._pop_idle_worker("envhash", tagged_only=True) is None
        # …but an exactly-tagged containerized worker is handed out
        tagged = agent_mod.WorkerHandle("w2", proc=FakeProc())
        tagged.registered.set()
        tagged.env_key = "envhash"
        a.idle_workers = [pristine, tagged]
        assert a._pop_idle_worker(
            "envhash", tagged_only=True) is tagged


@pytest.mark.skipif(not HAVE_ENGINE, reason="no podman/docker on this box")
class TestEndToEnd:
    def test_worker_starts_in_container(self):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=2)
        try:
            @ray_tpu.remote(runtime_env={
                "container": {"image": "python:3.12-slim"}})
            def whoami():
                import os

                return os.path.exists("/.dockerenv") or \
                    os.path.exists("/run/.containerenv")

            assert ray_tpu.get(whoami.remote(), timeout=300)
        finally:
            ray_tpu.shutdown()
