"""Generalized Advantage Estimation (reference:
rllib/evaluation/postprocessing.py compute_gae_for_sample_batch).

Vectorized over (T, E) rollout fragments in numpy; bootstrap from the value
of the final observation, with episode boundaries cutting the recursion.
"""

from __future__ import annotations

import numpy as np


def compute_gae(rewards: np.ndarray, values: np.ndarray,
                dones: np.ndarray, last_values: np.ndarray,
                gamma: float = 0.99, lam: float = 0.95):
    """rewards/values/dones: (T, E); last_values: (E,).

    Returns (advantages, value_targets), both (T, E).
    """
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    not_done = 1.0 - dones.astype(rewards.dtype)
    gae = np.zeros_like(last_values)
    next_values = last_values
    for t in range(T - 1, -1, -1):
        delta = rewards[t] + gamma * next_values * not_done[t] - values[t]
        gae = delta + gamma * lam * not_done[t] * gae
        adv[t] = gae
        next_values = values[t]
    return adv, adv + values
