from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig"]
