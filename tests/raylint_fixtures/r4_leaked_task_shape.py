"""R4 regression fixture: the leaked read-loop task (PRs 1/3).

The shipped bug: ``AsyncRpcClient`` connect paths did a bare
``loop.create_task(self._read_loop())`` and kept no reference. The event
loop holds tasks only weakly, so when a concurrent-spillback race
overwrote the client object, its read task was garbage-collected
mid-flight — the bench-tail "Task was destroyed but it is pending!" spam
— and any exception the loop raised was never observed.

R4 must flag the two discarded spawns below (bare statement and
assign-to-underscore) and must NOT flag the retained/tracked twins,
which are the shipped ``async_util.spawn_tracked`` discipline.
"""

import asyncio


class ReadLoopOwnerShape:
    """The bug: spawn the read loop, keep nothing."""

    def start(self, loop):
        loop.create_task(self._read_loop())  # expect-R4

    async def _read_loop(self):
        while True:
            await asyncio.sleep(1)


def spawn_and_forget(coro):
    _ = asyncio.ensure_future(coro)  # expect-R4


class TrackedOwnerShape:
    """The fix: the handle is retained (attribute / tracked set)."""

    def __init__(self):
        self._tasks = set()
        self._read_task = None

    def start(self, loop):
        self._read_task = loop.create_task(self._read_loop())
        self._tasks.add(loop.create_task(self._read_loop()))

    async def _read_loop(self):
        await asyncio.sleep(1)


async def awaited_inline():
    await asyncio.ensure_future(asyncio.sleep(0))
