"""XLA/device-mesh collective group — the TPU-native replacement for the
reference's NCCLGroup (reference:
python/ray/util/collective/collective_group/nccl_collective_group.py:127).

Design (SURVEY §2.5 / §5 "Distributed communication backend"):

- Within one group member (= one worker process = one TPU host), tensors may
  be ``jax.Array``s sharded over the member's **local device mesh**; the
  intra-member reduction lowers to ``jax.lax`` collectives over ICI via
  ``shard_map`` (see :meth:`_local_psum`).
- Across members, this class rides the host store (DCN control plane). On a
  real multi-host pod slice the preferred path is a *global* mesh formed by
  ``jax.distributed.initialize`` — then no per-op host hop exists at all and
  this group degenerates to rendezvous bookkeeping; see
  ``ray_tpu.train`` which uses exactly that path for gradient sync.

Results are returned as ``jax.Array``s placed with the input's sharding
(device_put), keeping the op functional.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ray_tpu.util.collective.collective_group.cpu_group import CPUGroup
from ray_tpu.util.collective.types import ReduceOp


def _is_jax(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.Array)
    except Exception:
        return False


class XLAGroup(CPUGroup):
    @classmethod
    def backend(cls) -> str:
        return "xla"

    def _to_wire(self, tensor) -> np.ndarray:
        if tensor is None:
            return None
        if _is_jax(tensor):
            import jax

            # Pull once to host for the cross-member (DCN) hop. A fully
            # addressable array is a cheap device->host copy; on multi-host
            # meshes the caller should be using the global-mesh path instead.
            return np.asarray(jax.device_get(tensor))
        return np.asarray(tensor)

    def _from_wire(self, array: np.ndarray, like):
        if like is not None and _is_jax(like):
            import jax

            return jax.device_put(
                array.astype(like.dtype), like.sharding)
        return super()._from_wire(array, like)

    # -- device-native helpers --------------------------------------------

    @staticmethod
    def local_psum(tensor, mesh, axis: str):
        """Reduce a per-device value over one axis of the member's local mesh
        — pure ICI traffic via ``jax.lax.psum`` under ``shard_map``."""
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(axis)
        return jax.jit(
            shard_map(
                lambda x: jax.lax.psum(x, axis),
                mesh=mesh, in_specs=(spec,), out_specs=P()))(tensor)

    def allreduce_sharded(self, tensor, mesh, axis: str,
                          op: ReduceOp = ReduceOp.SUM):
        """Hierarchical allreduce: ICI psum over the member's local mesh axis,
        then the cross-member combine (reference analog:
        nccl_collective_group allreduce_multigpu)."""
        local = self.local_psum(tensor, mesh, axis)
        from ray_tpu.util.collective.types import AllReduceOptions

        return self.allreduce(local, AllReduceOptions(reduceOp=op))
