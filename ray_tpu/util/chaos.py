"""Chaos / fault-injection utilities (reference:
python/ray/_private/test_utils.py:1431 ResourceKillerActor hierarchy and
python/ray/tests/chaos/ — periodic killers that chaos tests aim at the
cluster while a workload runs; recovery machinery, not the workload, is
what's under test).

Killers run in the DRIVER process on a background thread (they must
survive the very failures they inject — an actor-based killer can be
scheduled onto the node it kills). Targets come from the live cluster
state, so the same killer works against ``cluster_utils.Cluster``
fixtures and real deployments.
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

import ray_tpu


class ResourceKiller:
    """Base: periodically pick a target and kill it until stopped."""

    def __init__(self, interval_s: float = 1.0,
                 max_kills: Optional[int] = None,
                 seed: Optional[int] = None):
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.rng = random.Random(seed)
        self.kills: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- subclass hooks ----------------------------------------------------
    def find_target(self):
        raise NotImplementedError

    def kill_target(self, target) -> Optional[str]:
        """Kill; return a human-readable record or None if it got away."""
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> "ResourceKiller":
        def loop():
            while not self._stop.is_set():
                if self.max_kills is not None and \
                        len(self.kills) >= self.max_kills:
                    return
                try:
                    target = self.find_target()
                    if target is not None:
                        record = self.kill_target(target)
                        if record:
                            self.kills.append(record)
                except Exception:
                    pass  # the cluster may be mid-recovery; try again
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=type(self).__name__)
        self._thread.start()
        return self

    def stop(self) -> List[str]:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        return list(self.kills)


class WorkerKiller(ResourceKiller):
    """SIGKILL random task/actor worker processes on the local node
    (reference: WorkerKillerActor). Workers are discovered through the
    agent's ListWorkers RPC; the driver's own pid is never a target."""

    def __init__(self, interval_s: float = 1.0,
                 max_kills: Optional[int] = None,
                 seed: Optional[int] = None,
                 filter_fn: Optional[Callable[[dict], bool]] = None):
        super().__init__(interval_s, max_kills, seed)
        self.filter_fn = filter_fn

    def find_target(self):
        worker = ray_tpu._private.worker.global_worker
        reply = worker._acall(
            worker.agent.call("ListWorkers", {}), timeout=10)
        candidates = [
            w for w in (reply or [])
            if w.get("pid") and w["pid"] != os.getpid()
            # busy workers only: killing idle pool processes is no chaos
            and w.get("state") in ("LEASED", "ACTOR")
            and (self.filter_fn is None or self.filter_fn(w))
        ]
        return self.rng.choice(candidates) if candidates else None

    def kill_target(self, target) -> Optional[str]:
        try:
            os.kill(target["pid"], signal.SIGKILL)
            return f"worker pid={target['pid']}"
        except ProcessLookupError:
            return None


class NodeKiller(ResourceKiller):
    """Kill a random non-head node's agent process (reference:
    RayletKiller / EC2InstanceTerminator). Operates on a
    ``cluster_utils.Cluster`` so the process handles are killable."""

    def __init__(self, cluster, interval_s: float = 2.0,
                 max_kills: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(interval_s, max_kills, seed)
        self.cluster = cluster

    def find_target(self):
        nodes = [n for n in self.cluster.worker_nodes
                 if n.agent_proc and n.agent_proc.poll() is None]
        return self.rng.choice(nodes) if nodes else None

    def kill_target(self, target) -> Optional[str]:
        node_id = target.node_id
        self.cluster.remove_node(target, allow_graceful=False)
        return f"node {node_id[:12]}"


class DaemonKiller(ResourceKiller):
    """SIGKILL registered session daemons (agent / forkserver / gcs /
    worker) picked from the lifecycle pid registry — the chaos probe for
    the teardown supervisor itself: after a kill, fate-sharing must reap
    the victim's subtree and the session registry must converge to zero
    live pids on shutdown."""

    def __init__(self, session_dir: str, roles=("agent",),
                 interval_s: float = 2.0, max_kills: Optional[int] = None,
                 seed: Optional[int] = None, filter_fn=None):
        super().__init__(interval_s, max_kills, seed)
        self.session_dir = session_dir
        self.roles = tuple(roles)
        # optional registry-record predicate to pin the victim further
        # than role alone (e.g. "the worker hosting train rank 0")
        self.filter_fn = filter_fn

    def find_target(self):
        from ray_tpu._private import lifecycle

        candidates = [
            r for r in lifecycle.live_registered(self.session_dir)
            if r.get("role") in self.roles and r["pid"] != os.getpid()
            and (self.filter_fn is None or self.filter_fn(r))
        ]
        return self.rng.choice(candidates) if candidates else None

    def kill_target(self, target) -> Optional[str]:
        try:
            os.kill(target["pid"], signal.SIGKILL)
            return f"{target.get('role', 'daemon')} pid={target['pid']}"
        except ProcessLookupError:
            return None


class HeadKiller(ResourceKiller):
    """``kill -9`` the head control plane (GCS) at random points while a
    workload runs, then restart it after ``downtime_s`` — the chaos probe
    for the durable head plane (WAL + recovery reconciliation, ISSUE 8).
    Operates on a ``cluster_utils.Cluster`` head node whose process
    handle the driver owns; the restarted head resumes from the same
    ``RAY_TPU_GCS_PERSIST`` store and agents/drivers re-register through
    their watchdogs."""

    def __init__(self, cluster, downtime_s: float = 0.5,
                 interval_s: float = 5.0, max_kills: Optional[int] = None,
                 seed: Optional[int] = None, persist: Optional[str] = None):
        super().__init__(interval_s, max_kills, seed)
        self.cluster = cluster
        self.downtime_s = downtime_s
        self.persist = persist or os.environ.get("RAY_TPU_GCS_PERSIST", "")
        self.restarts = 0

    def find_target(self):
        node = self.cluster.head_node
        if node is None or node.head_proc is None \
                or node.head_proc.poll() is not None:
            return None
        return node

    def kill_target(self, target) -> Optional[str]:
        target.head_proc.kill()  # SIGKILL: no flush, no atexit
        target.head_proc.wait()
        time.sleep(self.downtime_s)
        self.restart_head(target)
        return f"head kill -9 + restart #{self.restarts}"

    def restart_head(self, node) -> None:
        import subprocess
        import sys

        from ray_tpu._private import lifecycle
        from ray_tpu._private.config import scrub_axon_bootstrap_env

        self.restarts += 1
        log = open(os.path.join(node.session_dir, "logs",
                                f"head_chaos_{self.restarts}.log"), "ab")
        env = scrub_axon_bootstrap_env(dict(os.environ))
        env["RAY_TPU_SESSION_DIR"] = node.session_dir
        env["RAY_TPU_PARENT_PID"] = str(os.getpid())
        if self.persist:
            env["RAY_TPU_GCS_PERSIST"] = self.persist
        node.head_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs",
             "--session-dir", node.session_dir,
             "--port", str(node.head_port)],
            stdout=log, stderr=log, env=env,
            start_new_session=True)
        # spawner-side registration (the child re-registers idempotently):
        # node.stop()'s sweep must reap the chaos-restarted head even if
        # it is killed again before its own register_self runs
        lifecycle.register_process(node.session_dir, "gcs",
                                   node.head_proc.pid)
        log.close()


class NetworkPartitioner(ResourceKiller):
    """Partition nodes off the cluster's NETWORK without touching their
    processes (built on protocol.FaultSchedule — reference lineage: the
    Jepsen/mesh-partition testing tradition the process killers above
    cannot reach). The victim's daemons stay alive and its sockets stay
    open; frames just stop flowing, which is exactly the failure mode —
    hung host, one-way link, gray failure — that RST-driven recovery
    paths never see.

    Requires the cluster to run with ``RAY_TPU_FAULT_INJECTION=1`` in the
    daemons' environment (set it before ``Cluster()``/``init()``); rules
    are published through ``<session_dir>/fault_schedule.json`` and
    picked up by every process within ``protocol.FAULT_POLL_S``.

    Modes: ``"both"`` (symmetric partition), ``"out"`` (one-way: the node
    hears the cluster but nothing it says gets out — heartbeats vanish,
    no RST), ``"in"`` (the node goes deaf). Unix sockets (worker ↔ local
    agent) are spared: the HOST is healthy, its network is not.

    Use directly (``partition(node_id)`` / ``heal()``) or as a periodic
    killer: each round partitions a random worker node for
    ``duration_s``, then heals it.
    """

    def __init__(self, cluster=None, session_dir: Optional[str] = None,
                 mode: str = "both", duration_s: float = 10.0,
                 interval_s: float = 5.0, max_kills: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(interval_s, max_kills, seed)
        if session_dir is None:
            if cluster is None:
                raise ValueError("need a cluster or a session_dir")
            session_dir = cluster.session_dir
        self.cluster = cluster
        self.session_dir = session_dir
        self.mode = mode
        self.duration_s = duration_s
        self.partitioned: Dict[str, str] = {}  # node_id -> mode
        self._rules_lock = threading.Lock()

    @property
    def fault_file(self) -> str:
        return os.path.join(self.session_dir, "fault_schedule.json")

    def _write_rules(self) -> None:
        rules = []
        for node_id, mode in self.partitioned.items():
            directions = {"both": ["both"], "out": ["out"],
                          "in": ["in"]}[mode]
            for direction in directions:
                rules.append({"self": node_id, "peer": "tcp",
                              "direction": direction, "method": "*",
                              "action": "drop"})
        tmp = self.fault_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rules": rules}, f)
        os.replace(tmp, self.fault_file)  # atomic: pollers never see a
        # half-written schedule

    def partition(self, node_id: str, mode: Optional[str] = None) -> None:
        """Cut node `node_id` off per `mode`, effective within one poll."""
        with self._rules_lock:
            self.partitioned[node_id] = mode or self.mode
            self._write_rules()

    def heal(self, node_id: Optional[str] = None) -> None:
        """Restore connectivity for one node (or all)."""
        with self._rules_lock:
            if node_id is None:
                self.partitioned.clear()
            else:
                self.partitioned.pop(node_id, None)
            self._write_rules()

    # -- ResourceKiller hooks ---------------------------------------------
    def find_target(self):
        head_id = None
        if self.cluster is not None and self.cluster.head_node is not None:
            head_id = self.cluster.head_node.node_id
        try:
            nodes = [n["node_id"] for n in ray_tpu.nodes()
                     if n["alive"] and n["node_id"] != head_id
                     and n["node_id"] not in self.partitioned]
        except Exception:
            return None
        return self.rng.choice(nodes) if nodes else None

    def kill_target(self, target) -> Optional[str]:
        self.partition(target)
        timer = threading.Timer(self.duration_s, self.heal, args=(target,))
        timer.daemon = True
        timer.start()
        return f"partition {target[:12]} mode={self.mode}"

    def stop(self) -> List[str]:
        kills = super().stop()
        self.heal()  # never leave a standing partition behind
        return kills


def kill_random_node(cluster, exclude_head: bool = True) -> Optional[str]:
    """One-shot helper (the `ray kill-random-node` CLI analog)."""
    killer = NodeKiller(cluster, max_kills=1)
    target = killer.find_target()
    if target is None:
        return None
    return killer.kill_target(target)
