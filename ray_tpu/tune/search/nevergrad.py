"""NevergradSearch adapter (reference: python/ray/tune/search/nevergrad/
nevergrad_search.py). Gated: `nevergrad` is not in this image's baked
package set — construction raises a clear ImportError."""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.tune.search.sample import Categorical, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


class NevergradSearch(Searcher):
    def __init__(self, space: Optional[Dict] = None,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 optimizer: str = "NGOpt", budget: int = 100, **kwargs):
        try:
            import nevergrad  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "NevergradSearch requires `nevergrad`, which is not "
                "installed in this environment. Use BasicVariantGenerator "
                "or the native TPE searcher instead.") from e
        super().__init__(metric, mode)
        self._space = space or {}
        self._optimizer_name = optimizer
        self._budget = budget
        self._candidates: Dict[str, object] = {}
        self._build()

    def _build(self) -> None:
        import nevergrad as ng

        params = {}
        self._constants: Dict[str, object] = {}
        for k, dom in self._space.items():
            if isinstance(dom, Categorical):
                params[k] = ng.p.Choice(list(dom.categories))
            elif isinstance(dom, Integer):
                params[k] = ng.p.Scalar(
                    lower=dom.lower,
                    upper=dom.upper - 1).set_integer_casting()
            elif isinstance(dom, Float):
                if getattr(dom, "log", False):
                    params[k] = ng.p.Log(lower=dom.lower, upper=dom.upper)
                else:
                    params[k] = ng.p.Scalar(lower=dom.lower,
                                            upper=dom.upper)
            else:
                self._constants[k] = dom
        self._opt = ng.optimizers.registry[self._optimizer_name](
            parametrization=ng.p.Dict(**params), budget=self._budget)

    def set_search_properties(self, metric, mode, config) -> bool:
        """Adopt the Tuner-supplied metric/mode/param_space (reference:
        nevergrad_search.py set_search_properties)."""
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = dict(config)
            self._build()
        return True

    def suggest(self, trial_id: str) -> Optional[Dict]:
        cand = self._opt.ask()
        self._candidates[trial_id] = cand
        out = dict(cand.value)
        out.update(self._constants)
        return out

    def on_trial_complete(self, trial_id, result=None,
                          error: bool = False) -> None:
        cand = self._candidates.pop(trial_id, None)
        if cand is None or error or not result or \
                self.metric not in result:
            return
        val = float(result[self.metric])
        # nevergrad minimizes; flip for max mode
        self._opt.tell(cand, -val if self.mode == "max" else val)
