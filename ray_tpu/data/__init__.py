"""ray_tpu.data — streaming distributed datasets (reference:
python/ray/data/read_api.py public surface).
"""

from __future__ import annotations

import builtins
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data import aggregate
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import (
    ActorPoolStrategy, Dataset, GroupedData, MaterializedDataset, from_blocks)
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data._internal.logical import Read
from ray_tpu.data import datasource as _ds

__all__ = [
    "Dataset", "MaterializedDataset", "DataIterator", "GroupedData",
    "ActorPoolStrategy", "BlockAccessor", "BlockMetadata", "aggregate",
    "range", "range_tensor", "from_items", "from_numpy", "from_pandas",
    "from_arrow", "from_blocks", "read_parquet", "read_csv", "read_json",
    "read_text", "read_binary_files", "read_numpy", "read_datasource",
    "read_tfrecords", "read_sql", "read_images", "read_webdataset",
    "read_mongo", "read_bigquery", "from_torch",
    "DataContext",
]



def read_datasource(source: _ds.Datasource, *,
                    parallelism: Optional[int] = None) -> Dataset:
    if parallelism is None:
        from ray_tpu.data.context import DataContext

        parallelism = DataContext.get_current().read_parallelism
    return Dataset(Read(source.get_read_tasks(parallelism),
                        name=source.name))


def range(n: int, *, parallelism: Optional[int] = None) -> Dataset:  # noqa: A001
    return read_datasource(_ds.RangeDatasource(n), parallelism=parallelism)


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(
        _ds.RangeDatasource(n, tensor_shape=tuple(shape), column="data"),
        parallelism=parallelism)


def from_items(items: List[Any], *, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(_ds.ItemsDatasource(list(items)),
                           parallelism=parallelism)


def from_numpy(arrays, column: str = "data") -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return from_blocks([{column: a} for a in arrays])


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa

    if not isinstance(dfs, list):
        dfs = [dfs]
    return from_blocks([
        pa.Table.from_pandas(df, preserve_index=False) for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return from_blocks(list(tables))


def read_parquet(paths, *, parallelism: Optional[int] = None, **kw) -> Dataset:
    return read_datasource(_ds.ParquetDatasource(paths, **kw),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: Optional[int] = None, **kw) -> Dataset:
    return read_datasource(_ds.CSVDatasource(paths, **kw),
                           parallelism=parallelism)


def read_json(paths, *, parallelism: Optional[int] = None, **kw) -> Dataset:
    return read_datasource(_ds.JSONDatasource(paths, **kw),
                           parallelism=parallelism)


def read_text(paths, *, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(_ds.TextDatasource(paths),
                           parallelism=parallelism)


def read_binary_files(paths, *, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(_ds.BinaryDatasource(paths),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(_ds.NumpyDatasource(paths),
                           parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(_ds.TFRecordDatasource(paths),
                           parallelism=parallelism)


def read_images(paths, *, size=None, mode: str = "RGB",
                parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(_ds.ImageDatasource(paths, size=size, mode=mode),
                           parallelism=parallelism)


def read_sql(sql: str, connection_factory, *,
             parallelism: Optional[int] = None) -> Dataset:
    """Read a SQL query through a DBAPI2 connection factory (reference:
    read_api.py:1902 read_sql). ``connection_factory`` is a zero-arg
    callable returning a fresh connection (e.g.
    ``lambda: sqlite3.connect(path)``) so every read task can connect from
    its own worker process."""
    return read_datasource(_ds.SQLDatasource(sql, connection_factory),
                           parallelism=parallelism)


def read_webdataset(paths, *, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(_ds.WebDatasetDatasource(paths),
                           parallelism=parallelism)


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None, parallelism: Optional[int] = None) -> Dataset:
    """Read a MongoDB collection (reference: read_api.py read_mongo;
    gated — requires ``pymongo`` at read time)."""
    return read_datasource(
        _ds.MongoDatasource(uri, database, collection, pipeline=pipeline),
        parallelism=parallelism)


def read_bigquery(project_id: str, *, query: Optional[str] = None,
                  dataset: Optional[str] = None,
                  parallelism: Optional[int] = None) -> Dataset:
    """Read a BigQuery query/dataset (reference: read_api.py
    read_bigquery; gated — requires ``google-cloud-bigquery``)."""
    return read_datasource(
        _ds.BigQueryDatasource(project_id, query=query, dataset=dataset),
        parallelism=parallelism)


def from_torch(torch_dataset) -> Dataset:
    """Materialize a (map-style) torch Dataset (reference:
    data/read_api.py from_torch)."""
    items = []
    for i in builtins.range(len(torch_dataset)):
        sample = torch_dataset[i]
        if isinstance(sample, tuple) and len(sample) == 2:
            items.append({"item": np.asarray(sample[0]),
                          "label": np.asarray(sample[1])})
        else:
            items.append({"item": np.asarray(sample)})
    return from_blocks([
        {k: np.stack([it[k] for it in items[s:s + 1000]])
         for k in items[0]}
        for s in builtins.range(0, len(items), 1000)])
