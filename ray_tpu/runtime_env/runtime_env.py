"""RuntimeEnv schema + validation (reference:
python/ray/runtime_env/runtime_env.py RuntimeEnv class)."""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional


class RuntimeEnvSetupError(Exception):
    """Raised when a runtime env cannot be set up on a worker."""


class RuntimeEnvConfig(dict):
    """Setup behavior knobs (reference: runtime_env.py RuntimeEnvConfig)."""

    KNOWN = {"setup_timeout_seconds", "eager_install"}

    def __init__(self, setup_timeout_seconds: int = 600,
                 eager_install: bool = True):
        super().__init__(setup_timeout_seconds=setup_timeout_seconds,
                         eager_install=eager_install)


class RuntimeEnv(dict):
    """Validated runtime environment spec; a plain dict on the wire."""

    KNOWN_FIELDS = {"env_vars", "working_dir", "py_modules", "pip", "conda",
                    "config", "excludes"}

    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Optional[Any] = None,
                 conda: Optional[Any] = None,
                 config: Optional[Dict] = None,
                 excludes: Optional[List[str]] = None,
                 **extra):
        super().__init__()
        for key, value in [("env_vars", env_vars), ("working_dir", working_dir),
                           ("py_modules", py_modules), ("pip", pip),
                           ("conda", conda), ("config", config),
                           ("excludes", excludes)]:
            if value is not None:
                self[key] = value
        # plugin fields (registered via register_plugin) pass through
        for key, value in extra.items():
            if value is not None:
                self[key] = value
        validate_runtime_env(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "RuntimeEnv":
        return cls(**d)


def validate_runtime_env(env: Dict) -> None:
    from ray_tpu.runtime_env.plugin import _PLUGINS

    for key in env:
        if key not in RuntimeEnv.KNOWN_FIELDS and key not in _PLUGINS:
            raise ValueError(
                f"unknown runtime_env field {key!r}; known: "
                f"{sorted(RuntimeEnv.KNOWN_FIELDS | set(_PLUGINS))}")
    ev = env.get("env_vars")
    if ev is not None:
        if not isinstance(ev, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items()):
            raise TypeError("env_vars must be a Dict[str, str]")
    wd = env.get("working_dir")
    if wd is not None:
        if not isinstance(wd, str):
            raise TypeError("working_dir must be a path string")
        if not (wd.startswith(("http://", "https://", "gs://", "s3://"))
                or os.path.isdir(wd)
                or (wd.endswith(".zip") and os.path.isfile(wd))):
            raise ValueError(
                f"working_dir {wd!r} is not a directory or .zip archive")
    pm = env.get("py_modules")
    if pm is not None:
        if not isinstance(pm, (list, tuple)):
            raise TypeError("py_modules must be a list of paths")
        for m in pm:
            if not isinstance(m, str) or not os.path.exists(m):
                raise ValueError(f"py_modules entry {m!r} does not exist")
    pip = env.get("pip")
    if pip is not None and not isinstance(pip, (list, dict, str)):
        raise TypeError("pip must be a list of requirements, a dict, or a "
                        "requirements-file path")
    if "container" in env and "conda" in env:
        # both are spawn-time interpreter choices; the agent can honor
        # only one (the reference rejects the combination the same way)
        raise ValueError(
            "runtime_env cannot combine 'container' and 'conda'")
    # every plugin-owned field validates through its plugin (container,
    # conda, third-party); built-ins default to a no-op validate
    for key, value in env.items():
        plugin = _PLUGINS.get(key)
        if plugin is not None:
            plugin.validate(value)
