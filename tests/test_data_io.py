"""Data IO extensions: TFRecords (pure-python codec), images,
iter_torch_batches, from_torch (reference: data/datasource/
tfrecords_datasource.py, image_datasource.py, iterator.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_tfrecord_roundtrip(ray4, tmp_path):
    ds = rd.from_items([
        {"x": float(i), "y": i, "name": f"row{i}"} for i in range(50)])
    ds.write_tfrecords(str(tmp_path))
    back = rd.read_tfrecords(str(tmp_path))
    rows = sorted(back.take_all(), key=lambda r: r["y"])
    assert len(rows) == 50
    assert rows[7]["y"] == 7
    assert abs(rows[7]["x"] - 7.0) < 1e-6
    assert rows[7]["name"] == b"row7"  # bytes (tf.Example has no str type)


def test_tfrecord_negative_ints_and_lists(tmp_path):
    from ray_tpu.data._internal.tfrecord import (
        read_tfrecord_file, write_tfrecord_file)

    rows = [{"a": np.array([-5, 3], np.int64),
             "f": np.array([0.5, -1.5], np.float32)}]
    write_tfrecord_file(str(tmp_path / "t.tfrecord"), iter(rows))
    got = list(read_tfrecord_file(str(tmp_path / "t.tfrecord")))[0]
    assert (got["a"] == [-5, 3]).all()
    assert np.allclose(got["f"], [0.5, -1.5])


def test_read_images(ray4, tmp_path):
    from PIL import Image

    for i in range(4):
        arr = np.full((8, 10, 3), i * 10, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img{i}.png")
    ds = rd.read_images(str(tmp_path), size=(4, 5))
    batch = ds.take_batch(4)
    assert batch["image"].shape == (4, 4, 5, 3)
    vals = sorted(int(im.mean()) for im in batch["image"])
    assert vals == [0, 10, 20, 30]


def test_read_webdataset(ray4, tmp_path):
    import io
    import json
    import tarfile

    from PIL import Image

    tar_path = tmp_path / "shard-000.tar"
    with tarfile.open(tar_path, "w") as tf:
        for i in range(3):
            img = Image.fromarray(np.full((4, 4, 3), i * 20, np.uint8))
            buf = io.BytesIO()
            img.save(buf, format="PNG")

            def add(name, data):
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))

            add(f"{i:04d}.png", buf.getvalue())
            add(f"{i:04d}.cls", str(i % 2).encode())
            add(f"{i:04d}.json", json.dumps({"idx": i}).encode())
    rows = sorted(rd.read_webdataset(str(tar_path)).take_all(),
                  key=lambda r: r["__key__"])
    assert len(rows) == 3
    assert rows[1]["png"].shape == (4, 4, 3)
    assert int(rows[1]["png"][0, 0, 0]) == 20
    assert rows[1]["cls"] == "1"
    assert rows[2]["json"]["idx"] == 2


def test_cli_serve_commands(ray4, tmp_path):
    """`ray-tpu serve deploy/status/shutdown` (reference: serve CLI)."""
    import json

    from ray_tpu.scripts.cli import main as cli_main

    cfg = {"applications": [{
        "import_path": "tests.test_serve_config:doubler_app",
        "name": "cliapp", "route_prefix": "/cli"}]}
    cfg_file = tmp_path / "serve.json"
    cfg_file.write_text(json.dumps(cfg))
    assert cli_main(["serve", "deploy", str(cfg_file)]) == 0
    assert cli_main(["serve", "status"]) == 0
    from ray_tpu import serve

    assert serve.status("cliapp")["status"] == "RUNNING"
    assert cli_main(["serve", "shutdown"]) == 0


def test_iter_torch_batches(ray4):
    import torch

    ds = rd.range(100)
    total = 0
    for batch in ds.iter_torch_batches(batch_size=32):
        assert isinstance(batch["id"], torch.Tensor)
        total += len(batch["id"])
    assert total == 100


def test_from_torch(ray4):
    import torch

    class TinySet(torch.utils.data.Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            return np.full((3,), i, np.float32), i % 2

    ds = rd.from_torch(TinySet())
    assert ds.count() == 10
    rows = ds.take(3)
    assert rows[1]["label"] in (0, 1)
    assert rows[1]["item"].shape == (3,)
