"""Continuous (iteration-level) batching engine for generative serving.

The static ``@serve.batch`` path batches whole requests: a batch forms,
runs to completion, and every slot is held hostage by the longest
generation in it. For token-by-token generation the standard production
shape is *continuous batching* (reference: vLLM / Ray Serve LLM
deployments; PAPER.md layer 11): the scheduler operates at STEP
granularity — each iteration advances every in-flight generation by one
step, finished requests leave the batch at the step boundary, and waiting
requests join at the next one. Short generations never wait for long
ones, and the hardware batch stays full under mixed-length load.

TPU deviations from the GPU-shaped reference:

- **Bucketed batch composition.** Jitted models compile per input shape,
  so the per-step batch is padded with ``None`` slots up to the smallest
  ``allowed_batch_sizes`` bucket that fits — the user's ``step_fn`` sees
  a fixed menu of batch widths and compiles once per bucket, exactly like
  ``@serve.batch``'s shape bucketing but applied every iteration.
- **Per-adapter grouping.** Multiplexed (LoRA-adapter) requests are
  grouped by model id: each step runs one adapter group, rotated
  round-robin, so a step applies a single adapter pytree to the whole
  batch instead of gathering per-row adapters.

The engine owns one background *stepper* thread. It is started lazily on
the first submit and EXITS when the engine sits idle (no running or
pending requests) for ``idle_timeout_s`` — an idle engine leaves no
daemon behind, which keeps the test suite's leak gate meaningful and
``serve.shutdown()`` clean. ``Replica.drain`` calls ``shutdown()``
explicitly before a scale-down kill.

User contract::

    def step_fn(model_id, states):  # states: List[Optional[state]]
        # padded to an allowed bucket with None; advance every real
        # state one iteration and return a same-length list whose real
        # slots are (emit, done) — emit is streamed to the caller
        # (skipped when None), done=True removes it from the batch.
        ...

    engine = ContinuousBatchingEngine(step_fn, max_batch_size=8,
                                      allowed_batch_sizes=(2, 4, 8))
    for token in engine.submit(payload, model_id="adapter-1"):
        ...
"""

from __future__ import annotations

import collections
import queue
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.exceptions import BackPressureError

_DONE = object()

# every live engine, for the leak gate: a stepper thread that outlives its
# workload (or the suite) is a bug the conftest session gate fails on
_live_engines: "weakref.WeakSet" = weakref.WeakSet()


def live_stepper_threads() -> List[str]:
    """Names of stepper threads still alive across all live engines."""
    out = []
    for eng in list(_live_engines):
        t = eng._thread
        if t is not None and t.is_alive():
            out.append(t.name)
    return out


class _EngineError:
    """Exception envelope on a request's output queue."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class _Request:
    __slots__ = ("payload", "model_id", "state", "out", "cancelled",
                 "joined_at")

    def __init__(self, payload: Any, model_id: str):
        self.payload = payload
        self.model_id = model_id
        self.state: Any = None
        self.out: "queue.SimpleQueue" = queue.SimpleQueue()
        self.cancelled = False
        self.joined_at = 0.0


class ContinuousBatchingEngine:
    def __init__(self, step_fn: Callable[[str, List], List], *,
                 max_batch_size: int = 8,
                 allowed_batch_sizes: Optional[Sequence[int]] = None,
                 prefill_fn: Optional[Callable[[Any, str], Any]] = None,
                 max_pending: Optional[int] = None,
                 idle_timeout_s: float = 0.5,
                 name: str = "engine"):
        self.step_fn = step_fn
        self.prefill_fn = prefill_fn
        self.allowed = (sorted(set(int(a) for a in allowed_batch_sizes))
                        if allowed_batch_sizes else None)
        self.max_batch_size = int(max_batch_size)
        if self.allowed:
            # the largest bucket caps the batch; buckets above the cap
            # would never dispatch
            self.allowed = [a for a in self.allowed
                            if a <= self.max_batch_size] or [
                                self.max_batch_size]
            self.max_batch_size = self.allowed[-1]
        self.max_pending = max_pending
        self.idle_timeout_s = idle_timeout_s
        self.name = name

        self._lock = threading.Lock()
        self._pending: "collections.deque[_Request]" = collections.deque()
        self._groups: Dict[str, List[_Request]] = {}
        self._rr: "collections.deque[str]" = collections.deque()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

        # counters (exposed via stats(); the replica folds them into its
        # health probe so the controller/bench see engine behavior)
        self._steps = 0
        self._emitted = 0
        self._completed = 0
        self._shed = 0
        self._max_batch_seen = 0
        self._padded_slots = 0
        _live_engines.add(self)

    # ---------------------------------------------------------------- public
    def bucket_for(self, n: int) -> int:
        """Smallest allowed batch size that fits n live requests."""
        if not self.allowed:
            return n
        for a in self.allowed:
            if a >= n:
                return a
        return self.allowed[-1]

    def submit(self, payload: Any, model_id: str = ""):
        """Enqueue one generation; returns a sync iterator of emitted
        items. Sheds with ``BackPressureError`` beyond ``max_pending``
        (the serve replica's admission queue is the usual bound — this
        cap protects direct/standalone engine users)."""
        req = _Request(payload, model_id)
        with self._lock:
            if self._stopped:
                raise RuntimeError(f"{self.name}: engine is shut down")
            if self.max_pending is not None:
                depth = len(self._pending) + sum(
                    len(g) for g in self._groups.values())
                if depth >= self.max_pending:
                    self._shed += 1
                    raise BackPressureError(
                        deployment=self.name,
                        queue_depths={self.name: depth})
        # prefill OUTSIDE the lock (and off the stepper thread): a
        # jit-compiling / forward-pass prefill must not block concurrent
        # submit()/stats()/shutdown() — stats() feeds the replica health
        # probe, and a multi-second stall there reads as "unhealthy"
        try:
            req.state = (self.prefill_fn(req.payload, req.model_id)
                         if self.prefill_fn is not None else req.payload)
        except BaseException as e:  # noqa: BLE001 — user prefill code
            req.out.put(_EngineError(e))
            return self._consume(req)
        with self._lock:
            if self._stopped:
                raise RuntimeError(f"{self.name}: engine is shut down")
            self._pending.append(req)
            self._ensure_thread_locked()
        self._wake.set()
        return self._consume(req)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            running = sum(len(g) for g in self._groups.values())
            return {
                "steps": self._steps, "emitted": self._emitted,
                "completed": self._completed, "shed": self._shed,
                "running": running, "pending": len(self._pending),
                "max_batch": self._max_batch_seen,
                "padded_slots": self._padded_slots,
            }

    def shutdown(self, timeout: float = 5.0) -> None:
        """Stop the stepper and fail all in-flight requests. Idempotent."""
        with self._lock:
            self._stopped = True
            doomed = list(self._pending)
            self._pending.clear()
            for g in self._groups.values():
                doomed.extend(g)
            self._groups.clear()
            self._rr.clear()
            t = self._thread
        self._wake.set()
        err = RuntimeError(f"{self.name}: engine shut down mid-generation")
        for req in doomed:
            req.out.put(_EngineError(err))
        if t is not None and t.is_alive() and \
                t is not threading.current_thread():
            t.join(timeout)

    # --------------------------------------------------------------- consume
    def _consume(self, req: _Request):
        def gen():
            try:
                while True:
                    item = req.out.get()
                    if item is _DONE:
                        return
                    if isinstance(item, _EngineError):
                        raise item.exc
                    yield item
            finally:
                # consumer went away (close/GC/exception): leave the
                # batch at the next step boundary instead of generating
                # tokens nobody reads
                req.cancelled = True

        return gen()

    # --------------------------------------------------------------- stepper
    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"serve-engine-{self.name}")
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                self._admit_locked()
                model_id, batch = self._select_locked()
                if batch is None and not self._pending:
                    # nothing to do: wait for work, exit when idle past
                    # the timeout (restarted lazily by the next submit)
                    self._wake.clear()
            if batch is None:
                if not self._wake.wait(self.idle_timeout_s):
                    with self._lock:
                        if not self._pending and not any(
                                self._groups.values()) \
                                and self._thread is \
                                threading.current_thread():
                            self._thread = None
                            return
                continue
            self._step(model_id, batch)

    def _admit_locked(self) -> None:
        """Join waiting requests at the step boundary, FIFO, capped by the
        per-group batch width."""
        skipped: List[_Request] = []
        while self._pending:
            req = self._pending.popleft()
            if req.cancelled:
                continue
            group = self._groups.get(req.model_id)
            if group is None:
                group = self._groups[req.model_id] = []
                self._rr.append(req.model_id)
            if len(group) >= self.max_batch_size:
                skipped.append(req)  # group full: wait for a leave
                continue
            req.joined_at = time.monotonic()
            group.append(req)
        self._pending.extendleft(reversed(skipped))

    def _select_locked(self):
        """Next adapter group, round-robin; drops empty groups."""
        for _ in range(len(self._rr)):
            if not self._rr:
                break
            mid = self._rr[0]
            self._rr.rotate(-1)
            group = self._groups.get(mid)
            if group:
                live = [r for r in group if not r.cancelled]
                if len(live) != len(group):
                    self._groups[mid] = live
                if live:
                    return mid, list(live[:self.max_batch_size])
            if not self._groups.get(mid):
                self._groups.pop(mid, None)
                try:
                    self._rr.remove(mid)
                except ValueError:
                    pass
        return None, None

    def _step(self, model_id: str, batch: List[_Request]) -> None:
        # flight recorder (ISSUE 14): one sampled `engine_step` slice per
        # iteration — batch size / bucket / pad in the extras answer
        # "where did serving time go" without any engine-specific probe
        from ray_tpu._private.events import REC as _rec

        ev_trace = _rec.new_trace() if _rec.enabled and _rec.sample() \
            else None
        ev_t0 = time.time() if ev_trace is not None else 0.0
        states: List[Optional[Any]] = [r.state for r in batch]
        bucket = self.bucket_for(len(states))
        pad = bucket - len(states)
        if pad > 0:
            states = states + [None] * pad
        try:
            results = self.step_fn(model_id, states)
        except BaseException as e:  # noqa: BLE001 — user step code
            with self._lock:
                group = self._groups.get(model_id, [])
                for r in batch:
                    try:
                        group.remove(r)
                    except ValueError:
                        pass
            for r in batch:
                r.out.put(_EngineError(e))
            return
        self._steps += 1
        if ev_trace is not None:
            _rec.record("engine_step::" + str(model_id), "serve", ev_t0,
                        time.time() - ev_t0, ev_trace[0], ev_trace[1], 0,
                        {"batch": len(batch), "bucket": bucket,
                         "pad": pad})
        self._max_batch_seen = max(self._max_batch_seen, len(batch))
        self._padded_slots += pad
        if results is None or len(results) < len(batch):
            err = ValueError(
                f"{self.name}: step_fn returned "
                f"{0 if results is None else len(results)} results for a "
                f"bucket of {bucket} ({len(batch)} live)")
            for r in batch:
                r.out.put(_EngineError(err))
            results = []
            finished = list(batch)
        else:
            finished = []
            for r, res in zip(batch, results):
                emit, done = (None, False) if res is None else res
                if emit is not None and not r.cancelled:
                    r.out.put(emit)
                    self._emitted += 1
                if done:
                    finished.append(r)
        if finished:
            with self._lock:
                group = self._groups.get(model_id, [])
                for r in finished:
                    try:
                        group.remove(r)
                    except ValueError:
                        pass
            for r in finished:
                r.out.put(_DONE)
                self._completed += 1
