"""R9 — a store mmap view escaping a function without a matching pin.

Invariant (the device object plane's view-lifetime contract, ISSUE 9):
a memoryview produced by the store read layer (``get_view`` /
``read_maybe_spilled``) aliases store memory whose lifetime the store
controls. A view that stays local to a function dies before the store
can move the object; a view that ESCAPES — returned, stored on
``self``, or captured by a nested function handed to the event loop —
outlives the call and can alias an evicted or spilled segment unless
the object is pinned for the view's lifetime. The zero-copy get path
ships exactly this shape (``Worker._pin_escaping_view``); the serve
path pins via its view-cache entry.

Detection (per module, heuristic but shaped on the shipped code):
inside every function that is not itself part of the store read layer
(``PRODUCER_NAMES``) and that performs no pin call (any call whose
attribute/function name contains ``pin`` — pin registration is
inherently name-adjacent in this codebase: ``pin``, ``PinObject``
pushes ride helper methods like ``_pin_escaping_view``), flag:

- ``return`` expressions containing a view variable or a direct
  producer call,
- assignments of either onto ``self``,
- view variables referenced inside a nested def/lambda (the capture
  outlives the frame — the task-leak shape applied to memory).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..callgraph import ProjectIndex
from ..model import ModuleInfo, Violation

RULE_ID = "R9"
SUMMARY = ("store mmap view escapes its function (returned / stored on "
           "self / captured by a nested function) without a pin — it can "
           "alias an evicted segment; pin the object for the view's "
           "lifetime")

# The store read layer: calls to these produce views; functions NAMED
# like these (or wrapping them, like the agent's tiered reader) are the
# producer layer itself and exempt — the contract binds their callers.
PRODUCER_NAMES = frozenset({
    "get_view", "read_maybe_spilled", "pinned_view", "pin_view",
})

# Calls that CONSUME a view into a fresh, non-aliasing value: passing
# the view through these is not an escape (``return len(view)`` copies
# nothing out of the segment).
SAFE_CONSUMERS = frozenset({
    "len", "bytes", "bytearray", "int", "bool", "float", "str", "hash",
    "sum", "min", "max", "repr", "hex",
})


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def _is_producer_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _call_name(node) in PRODUCER_NAMES


def _walk_own(node: ast.AST, *, into_nested: bool = False):
    """Walk a function body without descending into nested defs (their
    statements belong to their own pass) unless ``into_nested``."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if not into_nested and isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                      ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(cur))


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def check_module(mod: ModuleInfo, index: ProjectIndex) -> List[Violation]:
    out: List[Violation] = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name in PRODUCER_NAMES:
            continue
        # A pin anywhere in the function satisfies the contract for its
        # escapes (registration APIs are pin-named by convention).
        if any(isinstance(n, ast.Call) and "pin" in (
                _call_name(n) or "").lower()
               for n in _walk_own(fn, into_nested=True)):
            continue
        qn = mod.qualname(fn)
        # view variables: x = <recv>.get_view(...) etc.
        view_vars: Set[str] = set()
        for node in _walk_own(fn):
            if isinstance(node, ast.Assign) and _is_producer_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        view_vars.add(tgt.id)
        for node in _walk_own(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if _escapes(node.value, view_vars):
                    out.append(mod.violation(
                        RULE_ID, node,
                        f"'{qn}' returns a store view with no pin in "
                        f"scope — the caller's copy outlives this frame "
                        f"and can alias an evicted segment; pin the "
                        f"object for the view's lifetime (R9 view-"
                        f"lifetime contract)"))
            elif isinstance(node, ast.Assign):
                is_self_store = any(
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    for t in node.targets)
                if is_self_store and _escapes(node.value, view_vars):
                    out.append(mod.violation(
                        RULE_ID, node,
                        f"'{qn}' stores a store view on self with no pin "
                        f"in scope — the attribute outlives every call "
                        f"and can alias an evicted segment (R9)"))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                captured = view_vars & _names_in(node)
                if captured:
                    out.append(mod.violation(
                        RULE_ID, node,
                        f"nested function in '{qn}' captures store view "
                        f"'{sorted(captured)[0]}' with no pin in scope — "
                        f"the closure (a task, a callback) can run after "
                        f"the store moved the object (R9)"))
    return out


def _escapes(expr: ast.AST, view_vars: Set[str]) -> bool:
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call) and _call_name(node) in SAFE_CONSUMERS:
            continue  # consumed into a fresh value — nothing aliases
        if _is_producer_call(node):
            return True
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in view_vars:
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False
