"""Core-runtime microbenchmarks (reference: python/ray/_private/ray_perf.py:93
— the suite behind the release microbenchmark numbers in BASELINE.md:
single-client sync/async tasks, 1:1 and n:n actor calls, put/get).

Run: ``python -m ray_tpu._private.ray_perf [--filter substr]``
Prints one line per benchmark: ``name: N ops/s`` plus a JSON summary.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List

import numpy as np


def timeit(name: str, fn: Callable[[], None], multiplier: int = 1,
           min_time_s: float = 2.0) -> float:
    """Run fn repeatedly for ~min_time_s; returns ops/s
    (reference: ray_perf.py timeit)."""
    # warmup
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time_s:
        fn()
        count += 1
    took = time.perf_counter() - start
    rate = count * multiplier / took
    print(f"{name}: {rate:.1f} ops/s")
    return rate


def main(filter_substr: str = "") -> Dict[str, float]:
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)

    results: Dict[str, float] = {}

    def bench(name, fn, multiplier=1):
        if filter_substr and filter_substr not in name:
            return
        results[name] = timeit(name, fn, multiplier)

    # ---------------------------------------------------------------- tasks
    @ray_tpu.remote
    def noop():
        pass

    ray_tpu.get(noop.remote(), timeout=60)  # prime worker pool

    bench("single client tasks sync",
          lambda: ray_tpu.get(noop.remote()))

    # round sizes mirror the reference suite (reference ray_perf.py:204
    # submits 1000 per async round; :222-232 runs the n:n pattern through
    # m concurrent CLIENT worker processes) so the numbers are comparable
    # with BASELINE.md's
    N_ASYNC = 1000
    bench("single client tasks async",
          lambda: ray_tpu.get([noop.remote() for _ in range(N_ASYNC)]),
          multiplier=N_ASYNC)

    # ----------------------------------------------------------------- puts
    bench("single client put small",
          lambda: ray_tpu.put(b"x" * 100))

    arr = np.zeros((5 << 18,), np.float32)  # 5 MiB

    # hardware context for the put number: a put is bounded below by ONE
    # 5-MiB copy into the shm arena, so report this box's raw single-thread
    # copy bandwidth alongside (the reference's 19.45 GB/s figure came from
    # an m4.16xlarge with many memory channels)
    if not filter_substr or filter_substr in "raw memcpy gigabytes":
        dst = bytearray(arr.nbytes)
        src = memoryview(arr).cast("B")
        dst[:] = src
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < 1.0:
            dst[:] = src
            reps += 1
        mgbps = reps * arr.nbytes / (time.perf_counter() - t0) / 1e9
        print(f"raw memcpy gigabytes: {mgbps:.2f} GB/s")
        results["raw memcpy gigabytes"] = mgbps

    def put_large():
        for _ in range(10):
            ray_tpu.put(arr)

    t0 = time.perf_counter()
    if not filter_substr or filter_substr in "single client put gigabytes":
        n = 0
        while time.perf_counter() - t0 < 2.0:
            put_large()
            n += 1
        gbps = n * 10 * arr.nbytes / (time.perf_counter() - t0) / 1e9
        print(f"single client put gigabytes: {gbps:.2f} GB/s")
        results["single client put gigabytes"] = gbps

    ref = ray_tpu.put(arr)
    bench("single client get large",
          lambda: ray_tpu.get(ref))

    # multi client tasks async: m actor-clients each submit a batch of
    # noop TASKS from inside their own process (reference:
    # ray_perf.py:181-189 small_value_batch x4)
    N_MULTI, M_MULTI = 2500, 4

    @ray_tpu.remote
    class TaskClient:
        def submit_batch(self, n):
            ray_tpu.get([noop.remote() for _ in range(n)])

    # near-zero CPU: the clients must leave the pool's cores to the
    # tasks they submit (reference actors hold 0 CPU while alive)
    clients = [TaskClient.options(num_cpus=0.001).remote()
               for _ in range(M_MULTI)]
    for c in clients:
        ray_tpu.get(c.submit_batch.remote(2), timeout=120)
    bench("multi client tasks async",
          lambda: ray_tpu.get([c.submit_batch.remote(N_MULTI)
                               for c in clients], timeout=600),
          multiplier=N_MULTI * M_MULTI)
    for c in clients:
        ray_tpu.kill(c)

    # ---------------------------------------------------------------- actors
    @ray_tpu.remote
    class Actor:
        def noop(self):
            pass

    a = Actor.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)
    bench("1:1 actor calls sync", lambda: ray_tpu.get(a.noop.remote()))
    bench("1:1 actor calls async",
          lambda: ray_tpu.get([a.noop.remote() for _ in range(N_ASYNC)]),
          multiplier=N_ASYNC)

    actors = [Actor.remote() for _ in range(4)]
    for act in actors:
        ray_tpu.get(act.noop.remote(), timeout=60)

    # n:n = n CLIENTS x n actors: m concurrent driver-side `work` tasks
    # each fan N_NN calls over the actor pool from their own worker
    # process (reference: ray_perf.py:222-232 — `work.remote(actors)` x m)
    N_NN, M_NN = 1000, 4

    @ray_tpu.remote
    def work(actor_handles):
        ray_tpu.get([actor_handles[i % len(actor_handles)].noop.remote()
                     for i in range(N_NN)])

    bench("n:n actor calls async",
          lambda: ray_tpu.get([work.remote(actors) for _ in range(M_NN)]),
          multiplier=N_NN * M_NN)
    for act in actors + [a]:
        ray_tpu.kill(act)

    # flight-recorder A/B (ISSUE 14): the same async-task bench with the
    # recorder OFF (the default this suite runs under) vs ON at sample
    # rate 1.0 — the honest cost of full span recording — plus the
    # measured disabled-guard cost, which is what the <2% hard
    # requirement is actually about (you cannot A/B the disabled path
    # against "no instrumentation at runtime"; the guard probe times the
    # exact branch every site pays)
    if not filter_substr or "events" in filter_substr:
        from ray_tpu._private import events as _ev

        @ray_tpu.remote
        def noop_ev():
            pass

        ray_tpu.get(noop_ev.remote(), timeout=60)

        def run_batch():
            ray_tpu.get([noop_ev.remote() for _ in range(N_ASYNC)])

        off_rate = timeit("tasks async (events off)", run_batch,
                          multiplier=N_ASYNC)
        w = ray_tpu._worker_mod.global_worker
        armed = _ev.configure(w.session_dir or "/tmp", w.mode,
                              sample_rate=1.0)
        on_rate = timeit("tasks async (events on)", run_batch,
                         multiplier=N_ASYNC)
        _ev.REC.enabled = False  # restore the suite's default
        results["events ab"] = {
            "off_tasks_per_s": round(off_rate, 1),
            "on_tasks_per_s": round(on_rate, 1),
            "on_overhead_pct": round(
                (off_rate - on_rate) / off_rate * 100, 2) if off_rate else 0,
            "recorder_armed": armed,
            "disabled_guard_ns": round(_ev.overhead_probe(100_000), 1),
        }
        print(json.dumps({"events ab": results["events ab"]}))

    # direct-call transport columns (ISSUE 11): which lane the actor
    # benches above actually rode — shm frame counts prove same-node
    # calls bypassed loopback TCP; fallback counters prove the ladder
    # engaged rather than dropping frames
    try:
        from ray_tpu._private.mux import MUX_STATS
        from ray_tpu._private.shm_rpc import stats_snapshot

        transport = {
            "mux_sessions_opened": MUX_STATS["sessions_opened"],
            "mux_streams_opened": MUX_STATS["streams_opened"],
            **{f"shm_{k}": v for k, v in stats_snapshot().items()},
        }
        print(json.dumps({"transport": transport}))
        results["transport"] = transport  # type: ignore[assignment]
    except Exception:
        pass

    print(json.dumps(results))
    return results


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--filter", default="")
    args = parser.parse_args()
    main(args.filter)
