"""R12 regression fixture: lock-order cycles and the loop/GC Lock split.

The shipped shapes: PR 17's ``LineageLedger`` nests ledger-lock →
store-lock on the retain path and had to hand-roll evict-outside-the-
lock discipline so the store → ledger path could never close the cycle;
PR 5's MemoryStore deadlock was the loop/GC variant (plain Lock reached
from both an event-loop critical section and a GC-context destructor).

Shapes below:

- ``LedgerShape``/``StoreShape``/``EvictionListenerShape`` — a cycle
  *through a callback*: the ledger holds ``_lock`` and walks into the
  store's ``_mu`` (record → delete), while the store holds ``_mu`` and
  fires a registered eviction callback that walks back into the ledger
  (put → on_evict → record). Each direction is one ordering edge; both
  are flagged because together they form a 2-lock SCC.
- ``CacheShape`` — a plain ``Lock`` acquired in an ``async def`` (loop
  domain) and in ``__del__`` (GC domain) without the R1 RLock remedy;
  flagged at the loop-side acquisition.
- ``SafeCacheShape`` — same split but with the RLock fix: no flag.
- ``OrderedPairShape`` — two locks always taken in the same order on
  every path: edges but no cycle, no flag.
"""

import threading


class LedgerShape:
    """Holds its own lock, then walks into the store (lock → mu)."""

    def __init__(self, store):
        self._lock = threading.Lock()
        self._store = store
        self._entries = {}

    def record(self, key):
        with self._lock:
            self._entries[key] = True
            self._store.delete(key)  # expect-R12


class EvictionListenerShape:
    """The registered callback: fired by the store, re-enters the
    ledger. No locks of its own — just the hop that closes the cycle."""

    def __init__(self, ledger):
        self._ledger = ledger

    def on_evict(self, key):
        self._ledger.record(key)


class StoreShape:
    """Holds its own lock, then fires the callback (mu → lock)."""

    def __init__(self, listener):
        self._mu = threading.Lock()
        self._listener = listener
        self._table = {}

    def put(self, key, val):
        with self._mu:
            self._table[key] = val
            self._listener.on_evict(key)  # expect-R12

    def delete(self, key):
        with self._mu:
            self._table.pop(key, None)


class CacheShape:
    """The loop/GC split: plain Lock shared between an async handler
    and a destructor — the collector can fire ``__del__`` on the loop
    thread while ``insert`` is mid-critical-section."""

    def __init__(self):
        self._cache_lock = threading.Lock()
        self._items = {}

    async def insert(self, key, val):
        with self._cache_lock:  # expect-R12
            self._items[key] = val

    def __del__(self):
        with self._cache_lock:
            self._items.clear()


class SafeCacheShape:
    """The R1 remedy: RLock makes the loop/GC re-entry safe — no flag."""

    def __init__(self):
        self._cache_lock = threading.RLock()
        self._items = {}

    async def insert(self, key, val):
        with self._cache_lock:
            self._items[key] = val

    def __del__(self):
        with self._cache_lock:
            self._items.clear()


class OrderedPairShape:
    """Two locks, one global order on every path: edges, no cycle."""

    def __init__(self):
        self._outer = threading.Lock()
        self._inner = threading.Lock()
        self._n = 0

    def alpha(self):
        with self._outer:
            with self._inner:
                self._n += 1

    def beta(self):
        with self._outer:
            with self._inner:
                self._n -= 1
