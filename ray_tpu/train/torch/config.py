"""Torch backend: rendezvous + process group init (reference:
python/ray/train/torch/config.py:129 _TorchBackend — rank-0 address
broadcast then ``dist.init_process_group`` :91).

This image ships CPU torch, so gloo is the default (and only sensible)
backend; the TPU-native story remains JaxTrainer — TorchTrainer exists so
torch training code ports over unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train._internal.backend_executor import Backend
from ray_tpu.train._internal.worker_group import WorkerGroup


@dataclasses.dataclass
class TorchConfig:
    backend: str = "gloo"
    timeout_s: int = 1800

    @property
    def backend_cls(self):
        return TorchBackend


def _setup_torch_process_group(rank: int, world_size: int, master_addr: str,
                               master_port: int, backend: str,
                               timeout_s: int) -> None:
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    if not dist.is_initialized():
        dist.init_process_group(
            backend=backend, rank=rank, world_size=world_size,
            timeout=datetime.timedelta(seconds=timeout_s))


class TorchBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: TorchConfig) -> None:
        import ray_tpu

        metas = worker_group.node_metas()
        master_addr = metas[0]["hostname"]
        from ray_tpu.train._internal.util import find_free_port

        master_port = worker_group.execute_single(0, find_free_port)
        ray_tpu.get([
            w.execute.remote(_setup_torch_process_group, i,
                             len(worker_group), master_addr, master_port,
                             backend_config.backend,
                             backend_config.timeout_s)
            for i, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: TorchConfig) -> None:
        def teardown():
            try:
                import torch.distributed as dist

                if dist.is_initialized():
                    dist.destroy_process_group()
            except Exception:
                pass

        try:
            worker_group.execute(teardown)
        except Exception:
            pass
