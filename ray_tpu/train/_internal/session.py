"""Worker-side training session (reference:
python/ray/train/_internal/session.py — report :394/:654, world-rank
accessors). One ``_TrainSession`` lives per train-worker process; the user
loop talks to it through ``ray_tpu.train.report`` / ``get_context``."""

from __future__ import annotations

import os
import queue
import shutil
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint, InStoreCheckpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


class TrainingResult:
    REPORT = "report"
    DONE = "done"
    ERROR = "error"

    def __init__(self, kind: str, metrics: Optional[Dict] = None,
                 checkpoint_dir: Optional[str] = None,
                 error: Optional[str] = None,
                 shard_ref: Optional[Any] = None,
                 shard_step: Optional[int] = None,
                 shard_nbytes: int = 0):
        self.kind = kind
        self.metrics = metrics or {}
        self.checkpoint_dir = checkpoint_dir
        self.error = error
        # in-store checkpoint shard: the ObjectRef of this rank's packed
        # state at `shard_step` (rides the wire dict — refs serialize
        # through actor returns via the borrow protocol)
        self.shard_ref = shard_ref
        self.shard_step = shard_step
        self.shard_nbytes = int(shard_nbytes or 0)

    def to_wire(self) -> Dict:
        return {"kind": self.kind, "metrics": self.metrics,
                "checkpoint_dir": self.checkpoint_dir, "error": self.error,
                "shard_ref": self.shard_ref, "shard_step": self.shard_step,
                "shard_nbytes": self.shard_nbytes}

    @classmethod
    def from_wire(cls, d: Dict) -> "TrainingResult":
        return cls(d["kind"], d.get("metrics"), d.get("checkpoint_dir"),
                   d.get("error"), d.get("shard_ref"), d.get("shard_step"),
                   d.get("shard_nbytes") or 0)


class _TrainSession:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 local_world_size: int, node_rank: int,
                 experiment_name: str, storage_path: str,
                 trial_dir: str, config: Dict,
                 checkpoint: Optional[Checkpoint] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 checkpoint_shards: Optional[Dict] = None,
                 start_iteration: int = 0):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.experiment_name = experiment_name
        self.storage_path = storage_path
        self.trial_dir = trial_dir
        self.config = config
        self.loaded_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        # in-store resume manifest from the driver's CheckpointManager:
        # {"step": int, "world_size": int, "shards": {rank: ObjectRef}}.
        # The shard is pulled lazily on the first get_checkpoint() call so
        # N restarted workers hit the broadcast-tree pull path together.
        self.checkpoint_shards = checkpoint_shards
        self.result_queue: "queue.Queue[TrainingResult]" = queue.Queue()
        self.iteration = int(start_iteration)
        # Shard-ref keepalive: a put object's ownership record dies with
        # its last local ref, and the driver's AddBorrow registration for
        # a ref riding a return value is asynchronous — dropping our
        # handle at report time would free the shard before the driver
        # re-owns it. Held here until the driver acks (re-owned + pinned)
        # through get_next(release_upto=step).
        self._shard_refs: Dict[int, Any] = {}

    def report(self, metrics: Dict, checkpoint: Optional[Checkpoint] = None):
        ckpt_dir = None
        shard_ref = None
        shard_step = None
        shard_nbytes = 0
        if checkpoint is not None:
            from ray_tpu._private.config import CONFIG

            if isinstance(checkpoint, InStoreCheckpoint):
                # store-only: one zero-copy put of the packed shard; the
                # driver re-owns + pins it in CheckpointManager. Nothing
                # touches disk on this path.
                import ray_tpu

                shard_ref = ray_tpu.put(checkpoint.buffer)
                shard_step = self.iteration
                shard_nbytes = len(memoryview(checkpoint.buffer).cast("B"))
                self._shard_refs[shard_step] = shard_ref
            else:
                ckpt_dir = self._persist_to_trial_dir(checkpoint)
                if CONFIG.train_in_store_checkpoints:
                    # disk checkpoints also get an in-store shard so a
                    # restart can restore without disk reads
                    import ray_tpu
                    from ray_tpu.train._internal.util import pack_dir

                    buf = pack_dir(checkpoint.path)
                    shard_ref = ray_tpu.put(buf)
                    shard_step = self.iteration
                    shard_nbytes = len(memoryview(buf).cast("B"))
                    self._shard_refs[shard_step] = shard_ref
        self.iteration += 1
        self.result_queue.put(
            TrainingResult(TrainingResult.REPORT, metrics, ckpt_dir,
                           shard_ref=shard_ref, shard_step=shard_step,
                           shard_nbytes=shard_nbytes))

    def _persist_to_trial_dir(self, checkpoint: Checkpoint) -> str:
        # Persist into the trial dir (StorageContext analog: reference
        # train/_internal/storage.py:99-111). Only rank 0 uploads in
        # the common fully-replicated case; other ranks may still pass
        # shard checkpoints which land in per-rank subdirs. When the
        # trial dir is a remote URI, THIS worker process uploads its
        # own shards directly (upload-from-worker: on a pod each host
        # pushes to the bucket; nothing round-trips the driver).
        from ray_tpu._private.storage import (
            get_storage_backend, is_remote_uri, join_uri)

        name = f"checkpoint_{self.iteration:06d}"
        if is_remote_uri(self.trial_dir):
            sub = [] if self.world_rank == 0 \
                else [f"rank_{self.world_rank}"]
            dest = join_uri(self.trial_dir, name, *sub)
            get_storage_backend(dest).upload_dir(checkpoint.path, dest)
            return join_uri(self.trial_dir, name)
        if self.world_rank == 0:
            dest = os.path.join(self.trial_dir, name)
        else:
            dest = os.path.join(self.trial_dir, name,
                                f"rank_{self.world_rank}")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if os.path.abspath(checkpoint.path) != os.path.abspath(dest):
            shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        return os.path.join(self.trial_dir, name)

    def release_shards(self, upto_step: int) -> None:
        """Driver ack: shards up to ``upto_step`` have been re-owned and
        pinned driver-side; this worker's copies may be reclaimed."""
        for step in [s for s in self._shard_refs if s <= upto_step]:
            del self._shard_refs[step]

    def drop_object_refs(self) -> None:
        """Release every store ref the session holds — keepalive shards,
        the restore manifest, the memoized restored checkpoint. Called
        when the train fn ends, WHILE the actor's owner connections are
        still up: a borrowed ref's RemoveBorrow rides ObjectRef GC, and
        an actor killed before GC runs would leave the driver's borrow
        count stuck forever (the owned shard bytes would never free)."""
        import gc

        self._shard_refs.clear()
        self.checkpoint_shards = None
        self.loaded_checkpoint = None
        gc.collect()

    def get_checkpoint(self) -> Optional[Checkpoint]:
        if self.checkpoint_shards:
            ckpt = self._restore_in_store()
            if ckpt is not None:
                return ckpt
        return self.loaded_checkpoint

    def _restore_in_store(self) -> Optional[Checkpoint]:
        """Pull this rank's shard from the in-store manifest (broadcast
        tree forms automatically when every restarted rank pulls the same
        large object). Falls back to rank-0's shard when this rank is new
        (elastic grow) or its old shard is missing — the replicated-state
        contract: rank 0's shard must be loadable by any rank."""
        import ray_tpu
        from ray_tpu._private.events import REC

        manifest = self.checkpoint_shards
        shards = {int(k): v
                  for k, v in (manifest.get("shards") or {}).items()}
        ref = shards.get(self.world_rank, shards.get(0))
        if ref is None:
            return None
        t0 = time.time()
        sampled = REC.sample()
        try:
            buf = ray_tpu.get(ref)
        except Exception:
            # shard lost (owner died with the old driver, store eviction
            # raced the pin): fall back to any disk checkpoint
            return None
        ckpt = InStoreCheckpoint(buf, ref=ref,
                                 step=int(manifest.get("step") or 0))
        if sampled:
            tid, sid = REC.new_trace()
            REC.record("train_resume::restore", "train", t0,
                       time.time() - t0, tid, sid,
                       extra={"rank": self.world_rank, "step": ckpt.step,
                              "nbytes": len(memoryview(buf).cast("B"))})
        # memoize: repeated get_checkpoint() calls in the loop must not
        # re-pull; the first pull already landed in the local store
        self.loaded_checkpoint = ckpt
        self.checkpoint_shards = None
        return ckpt

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}")
        return shard


class TrainContext:
    """What ``ray_tpu.train.get_context()`` returns inside a worker
    (reference: ray.train.get_context TrainContext)."""

    def get_world_rank(self) -> int:
        return get_session().world_rank

    def get_world_size(self) -> int:
        return get_session().world_size

    def get_local_rank(self) -> int:
        return get_session().local_rank

    def get_local_world_size(self) -> int:
        return get_session().local_world_size

    def get_node_rank(self) -> int:
        return get_session().node_rank

    def get_experiment_name(self) -> str:
        return get_session().experiment_name

    def get_trial_dir(self) -> str:
        return get_session().trial_dir

    def get_storage(self):
        return get_session().storage_path


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(**kwargs)
        return _session


def get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "Not inside a ray_tpu.train session — this API must be called "
            "from within train_loop_per_worker")
    return _session


def shutdown_session() -> None:
    global _session
    with _session_lock:
        _session = None


def in_session() -> bool:
    return _session is not None
