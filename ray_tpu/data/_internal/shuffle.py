"""All-to-all bulk implementations: repartition, random_shuffle, sort,
groupby-aggregate (reference: python/ray/data/_internal/planner/exchange/ —
map-stage partitions each block, reduce-stage merges per output partition).

Each returns a ``bulk_fn(bundles) -> bundles`` closure run by
``AllToAllOperator`` at the barrier. Map/reduce stages are ray_tpu tasks, so
the exchange parallelizes across the cluster like the reference's
push-based shuffle.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data._internal.physical import RefBundle


def _get_many(refs):
    return ray_tpu.get(list(refs))


# -------------------------------------------------------------- repartition
def _slice_concat_task(parts: List[Tuple[Any, int, int]]):
    """parts: (block_ref, start, end) triples → one output block."""
    # one batched get: every source pull starts in the same WaitObjects
    # window instead of paying a sequential round trip per part
    blocks_in = ray_tpu.get([ref for ref, _, _ in parts])
    blocks = [BlockAccessor(b).slice(start, end)
              for b, (_, start, end) in zip(blocks_in, parts)]
    out = BlockAccessor.concat(blocks)
    return out, BlockAccessor(out).metadata()


def repartition_fn(num_blocks: int) -> Callable:
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        total = sum(b.meta.num_rows for b in bundles)
        # Global row-range split: output i covers [i*total/n, (i+1)*total/n).
        bounds = [(i * total) // num_blocks for i in range(num_blocks + 1)]
        # For each output, find the (block, start, end) spans covering it.
        starts = []
        acc = 0
        for b in bundles:
            starts.append(acc)
            acc += b.meta.num_rows
        out_refs = []
        for i in range(num_blocks):
            lo, hi = bounds[i], bounds[i + 1]
            parts = []
            for b, s in zip(bundles, starts):
                e = s + b.meta.num_rows
                a, z = max(lo, s), min(hi, e)
                if a < z:
                    parts.append((b.block_ref, a - s, z - s))
            out_refs.append(ray_tpu.remote(_slice_concat_task)
                            .options(name="Data::Repartition",
                                     num_returns=2).remote(parts))
        # payloads stay in the object store; metadata comes back in ONE
        # batched get (the per-bundle blocking get serialized the whole
        # repartition behind its slowest predecessor — ISSUE 12)
        metas = ray_tpu.get([r[1] for r in out_refs])
        return [RefBundle(r[0], meta)
                for r, meta in zip(out_refs, metas)]

    return bulk


# ----------------------------------------------------------- random shuffle
def _shuffle_map(block: Block, n: int, seed: Optional[int], salt: int):
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    rng = np.random.default_rng(None if seed is None else seed + salt)
    assign = rng.integers(0, n, rows)
    perm = rng.permutation(rows)
    shards = []
    for i in range(n):
        idx = perm[assign[perm] == i]
        shards.append(acc.take_indices(idx))
    return shards


def _shuffle_reduce(map_refs, i: int, seed: Optional[int]):
    # one batched get: all map outputs pull in one WaitObjects window
    shards = [b[i] for b in ray_tpu.get(list(map_refs))]
    out = BlockAccessor.concat(shards)
    acc = BlockAccessor(out)
    rng = np.random.default_rng(None if seed is None else seed * 7919 + i)
    out = acc.take_indices(rng.permutation(acc.num_rows()))
    return out, BlockAccessor(out).metadata()


def _exchange_remote_args():
    """The shuffle map/reduce pinning knobs apply to BOTH exchange
    implementations so streaming-vs-materializing comparisons (and the
    data_shuffle bench) measure the exchange, not task placement."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    return (dict(ctx.shuffle_map_remote_args or {}),
            dict(ctx.shuffle_reduce_remote_args or {}))


def random_shuffle_fn(seed: Optional[int] = None,
                      num_blocks: Optional[int] = None) -> Callable:
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        if not bundles:
            return []
        map_args, red_args = _exchange_remote_args()
        n = num_blocks or len(bundles)
        map_refs = [
            ray_tpu.remote(_shuffle_map).options(
                name="Data::ShuffleMap", **map_args)
            .remote(b.block_ref, n, seed, salt)
            for salt, b in enumerate(bundles)]
        red_refs = [
            ray_tpu.remote(_shuffle_reduce).options(
                name="Data::ShuffleReduce", num_returns=2, **red_args)
            .remote(map_refs, i, seed)
            for i in range(n)]
        metas = ray_tpu.get([r[1] for r in red_refs])
        return [RefBundle(r[0], meta)
                for r, meta in zip(red_refs, metas)]

    return bulk


# -------------------------------------------------------------------- sort
def _sample_task(block: Block, key, k: int):
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return np.asarray([])
    idx = np.linspace(0, n - 1, min(k, n)).astype(np.int64)
    col = acc.to_numpy_dict()[key if isinstance(key, str) else key[0]]
    return col[idx]


def _sort_map(block: Block, key, boundaries):
    acc = BlockAccessor(block)
    first = key if isinstance(key, str) else key[0]
    col = acc.to_numpy_dict()[first]
    assign = np.searchsorted(boundaries, col, side="right")
    return [acc.take_indices(np.nonzero(assign == i)[0])
            for i in range(len(boundaries) + 1)]


def _sort_reduce(map_refs, i: int, key, descending: bool):
    shards = [b[i] for b in ray_tpu.get(list(map_refs))]
    out = BlockAccessor.concat(shards)
    acc = BlockAccessor(out)
    if acc.num_rows():
        out = acc.take_indices(acc.sort_indices(key, descending))
    return out, BlockAccessor(out).metadata()


def sort_fn(key: Union[str, List[str]], descending: bool = False) -> Callable:
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        if not bundles:
            return []
        n = len(bundles)
        samples = ray_tpu.get([
            ray_tpu.remote(_sample_task).remote(b.block_ref, key, 20)
            for b in bundles])
        allsamp = np.sort(np.concatenate([s for s in samples if len(s)]))
        if len(allsamp) == 0:
            return bundles
        q = np.linspace(0, len(allsamp) - 1, n + 1)[1:-1].astype(np.int64)
        boundaries = allsamp[q]
        map_refs = [ray_tpu.remote(_sort_map).options(name="Data::SortMap")
                    .remote(b.block_ref, key, boundaries) for b in bundles]
        red_refs = [ray_tpu.remote(_sort_reduce)
                    .options(name="Data::SortReduce", num_returns=2)
                    .remote(map_refs, i, key, descending) for i in range(n)]
        order = range(n - 1, -1, -1) if descending else range(n)
        metas = ray_tpu.get([r[1] for r in red_refs])
        return [RefBundle(red_refs[i][0], metas[i]) for i in order]

    return bulk


# ------------------------------------------------------------- groupby/agg
def _hash_partition(block: Block, key: str, n: int):
    import zlib

    acc = BlockAccessor(block)
    col = acc.to_numpy_dict()[key]
    if col.dtype.kind in "OUS":
        # crc32, not hash(): Python's str hash is salted per process, and
        # map tasks for different blocks run in different workers — the same
        # key must land in the same partition everywhere.
        hashes = np.asarray(
            [zlib.crc32(str(x).encode()) % n for x in col])
    else:
        hashes = np.abs(col.astype(np.int64, copy=False)) % n
    return [acc.take_indices(np.nonzero(hashes == i)[0]) for i in range(n)]


def _agg_reduce(map_refs, i: int, key: str, agg_blobs: bytes):
    import cloudpickle

    aggs = cloudpickle.loads(agg_blobs)
    shards = [b[i] for b in ray_tpu.get(list(map_refs))]
    merged = BlockAccessor.concat(shards)
    acc = BlockAccessor(merged)
    nd = acc.to_numpy_dict()
    if acc.num_rows() == 0:
        return BlockAccessor.batch_to_block({}), BlockAccessor({}).metadata()
    col = nd[key]
    uniq, inverse = np.unique(col, return_inverse=True)
    out: Dict[str, np.ndarray] = {key: uniq}
    for agg in aggs:
        vals = []
        src = nd[agg.on] if agg.on else None
        for g in range(len(uniq)):
            mask = inverse == g
            vals.append(agg.apply(
                {k: v[mask] for k, v in nd.items()}, src[mask]
                if src is not None else None))
        out[agg.output_name(key)] = np.asarray(vals)
    block = BlockAccessor.batch_to_block(out)
    return block, BlockAccessor(block).metadata()


def groupby_agg_fn(key: str, aggs: List[Any],
                   num_partitions: Optional[int] = None) -> Callable:
    import cloudpickle

    blobs = cloudpickle.dumps(aggs)

    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        if not bundles:
            return []
        n = num_partitions or min(len(bundles), 8)
        map_refs = [ray_tpu.remote(_hash_partition)
                    .options(name="Data::GroupByMap")
                    .remote(b.block_ref, key, n) for b in bundles]
        red_refs = [ray_tpu.remote(_agg_reduce)
                    .options(name="Data::GroupByReduce", num_returns=2)
                    .remote(map_refs, i, key, blobs) for i in range(n)]
        metas = ray_tpu.get([r[1] for r in red_refs])
        return [RefBundle(r[0], meta)
                for r, meta in zip(red_refs, metas) if meta.num_rows]

    return bulk


# ---------------------------------------------------------------- global agg
def global_agg_fn(aggs: List[Any]) -> Callable:
    """Aggregate with no grouping → a single one-row block."""
    import cloudpickle

    blobs = cloudpickle.dumps(aggs)

    def _partial(block: Block, blob: bytes):
        aggs = cloudpickle.loads(blob)
        nd = BlockAccessor(block).to_numpy_dict()
        return [a.partial(nd) for a in aggs]

    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        partial_refs = [ray_tpu.remote(_partial).remote(b.block_ref, blobs)
                        for b in bundles]
        partials = ray_tpu.get(partial_refs)
        out = {}
        for i, agg in enumerate(aggs):
            out[agg.output_name(None)] = np.asarray(
                [agg.finalize([p[i] for p in partials])])
        block = BlockAccessor.batch_to_block(out)
        return [RefBundle(ray_tpu.put(block), BlockAccessor(block).metadata())]

    return bulk
