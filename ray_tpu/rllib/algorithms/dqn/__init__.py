from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig

__all__ = ["DQN", "DQNConfig"]
