"""Core data model shared by the lint engine, rules, and baseline manager."""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

# `# raylint: disable=R1` or `# raylint: disable=R1,R4 -- reason`
_DISABLE_RE = re.compile(r"#\s*raylint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclass(frozen=True)
class Violation:
    """One rule hit, attributed to a source location.

    ``key()`` deliberately excludes the line number: baseline entries must
    survive unrelated edits above the flagged statement, so identity is
    (file, rule, enclosing symbol, normalized source text) plus an
    occurrence index assigned by the baseline manager for duplicates.
    """

    rule: str       # "R1".."R8"
    path: str       # project-relative posix path
    line: int       # 1-based
    col: int
    message: str
    symbol: str     # enclosing qualname ("MemoryStore.put", "<module>")
    snippet: str    # stripped source of the flagged line

    def key(self) -> str:
        norm = " ".join(self.snippet.split())
        return f"{self.path}::{self.rule}::{self.symbol}::{norm}"

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
            "snippet": self.snippet,
            "key": self.key(),
        }

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.symbol}] {self.message}")


class ModuleInfo:
    """Parsed view of one source file: AST + parent links + disable map.

    Parent links let rules walk *up* (is this call inside a lambda passed
    to retry_call? is this create_task a bare statement?), which plain
    ``ast.walk`` cannot answer.
    """

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.disables: Dict[int, Set[str]] = self._parse_disables()

    def _parse_disables(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _DISABLE_RE.search(line)
            if m:
                rules = {r.strip().upper() for r in m.group(1).split(",")
                         if r.strip()}
                out[i] = rules
        return out

    def is_disabled(self, rule: str, line: int) -> bool:
        """A violation at ``line`` is suppressed by a disable comment on
        the line itself or anywhere in the contiguous block of comment
        lines directly above it (multi-line justifications are the
        expected idiom: ``# raylint: disable=R6 -- long-poll by design:``
        followed by continuation comment lines)."""
        if self._has_disable(rule, line):
            return True
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].strip().startswith("#"):
            if self._has_disable(rule, ln):
                return True
            ln -= 1
        return False

    def _has_disable(self, rule: str, line: int) -> bool:
        rules = self.disables.get(line)
        return bool(rules and (rule in rules or "ALL" in rules))

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def qualname(self, node: ast.AST) -> str:
        """Dotted class/function path enclosing ``node`` ('<module>' at
        top level)."""
        parts: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(anc.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            parts.insert(0, node.name)
        return ".".join(reversed(parts)) or "<module>"

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def violation(self, rule: str, node: ast.AST, message: str) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Violation(rule=rule, path=self.relpath, line=line, col=col,
                         message=message, symbol=self.qualname(node),
                         snippet=self.snippet_at(line))


@dataclass
class LintResult:
    """Outcome of one lint run, pre-split by the baseline manager."""

    violations: List[Violation] = field(default_factory=list)   # unsuppressed
    grandfathered: List[Violation] = field(default_factory=list)
    suppressed_count: int = 0      # inline-disabled
    stale_baseline: List[str] = field(default_factory=list)     # unmatched keys
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.violations and not self.parse_errors

    def to_dict(self) -> Dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "elapsed_s": round(self.elapsed_s, 3),
            "violations": [v.to_dict() for v in self.violations],
            "grandfathered": [v.to_dict() for v in self.grandfathered],
            "suppressed_count": self.suppressed_count,
            "stale_baseline": list(self.stale_baseline),
            "parse_errors": list(self.parse_errors),
        }
