"""Actor scale-out (ISSUE 10): warm worker pools, batched
lease/registration RPCs, and O(1) scheduler state.

Unit layers (no cluster): warm-pool lease handout liveness (conn-closed
and death-ledger pids are never leased), the forkserver death-ledger
consumer, idle-TTL reap accounting, the batch-size histogram, the
head's incremental scheduler indexes (state counts, node/job buckets,
committed-resources ledger, utilization rank), and the
CreateActorBatch/ActorReadyBatch framing round trip against a
HeadServer with fake connections.

Integration: a 200-actor burst rides the warm pool (hit counter
asserted) with batched readiness reports; DaemonKiller-style SIGKILL of
a parked warm worker and then of a just-leased worker degrades to cold
forks — creation still completes, no hang (the pid-registry-converges
check is the conftest session leak gate). A parked warm worker must
never have imported jax (MULTICHIP dryrun gate contract).
"""

import asyncio
import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu._private.agent import WorkerHandle, _ForeignProc, _note_hist
from ray_tpu._private.gcs import (
    ACTOR_ALIVE, ACTOR_DEAD, ACTOR_PENDING, HeadServer, _NodeRank)
from ray_tpu._private.resources import ResourceSet


# ---------------------------------------------------------------------------
# unit: warm pool handout + death ledger
# ---------------------------------------------------------------------------
class _FakeConn:
    closed = False

    def __init__(self):
        self.meta = {}
        self.pushes = []

    async def push(self, method, payload):
        self.pushes.append((method, payload))

    def push_nowait(self, method, payload):
        self.pushes.append((method, payload))


def _mini_agent(tmp_path):
    """A NodeAgent with real state tables but no started loops/servers."""
    from ray_tpu._private.agent import NodeAgent

    store = tmp_path / "store"
    sess = tmp_path / "session"
    os.makedirs(store, exist_ok=True)
    os.makedirs(sess, exist_ok=True)
    return NodeAgent(
        node_id="deadbeef" * 4, session_dir=str(sess), store_dir=str(store),
        head_host="127.0.0.1", head_port=1, resources={"CPU": 4.0},
        object_store_memory=1 << 20)


def _registered_handle(pid=0):
    h = WorkerHandle(os.urandom(16).hex(), proc=_ForeignProc(pid))
    h.registered.set()
    h.conn = _FakeConn()
    return h


class TestWarmPoolUnits:
    def test_lease_prefers_live_pristine_worker(self, tmp_path):
        agent = _mini_agent(tmp_path)
        live = _registered_handle(pid=os.getpid())
        agent.idle_workers.append(live)
        agent.workers[live.worker_id] = live
        got = agent._lease_warm_worker()
        assert got is live
        assert agent.idle_workers == []

    def test_closed_conn_is_never_leased(self, tmp_path):
        agent = _mini_agent(tmp_path)
        stale = _registered_handle(pid=os.getpid())
        stale.conn.closed = True
        agent.idle_workers.append(stale)
        assert agent._lease_warm_worker() is None

    def test_death_ledger_pid_is_never_leased(self, tmp_path):
        """A warm worker reaped by the forkserver's SIGCHLD handler has
        no connection to drop and its pid may be recycled — the ledger
        is the only truthful death signal for that window."""
        agent = _mini_agent(tmp_path)
        # pid of THIS process: kill(pid, 0) says alive, i.e. exactly the
        # recycled-pid shape the ledger exists to catch
        victim = _registered_handle(pid=os.getpid())
        agent.idle_workers.append(victim)
        agent.workers[victim.worker_id] = victim
        agent._pid_handles[os.getpid()] = victim
        with open(agent._forkserver_sock + ".deaths", "w") as f:
            f.write(f"{os.getpid()}\n")

        async def run():
            assert agent._lease_warm_worker() is None
            # the exit handler was scheduled; let it run
            await asyncio.sleep(0)

        asyncio.run(run())
        assert victim.force_dead
        assert not victim.alive

    def test_ledger_consumed_incrementally(self, tmp_path):
        agent = _mini_agent(tmp_path)
        path = agent._forkserver_sock + ".deaths"
        with open(path, "w") as f:
            f.write("999999999\n")

        async def run():
            agent._consume_death_ledger()
            pos = agent._death_ledger_pos
            agent._consume_death_ledger()  # nothing new: offset stable
            assert agent._death_ledger_pos == pos

        asyncio.run(run())

    def test_warm_target_auto_and_disable(self, tmp_path, monkeypatch):
        agent = _mini_agent(tmp_path)
        monkeypatch.setenv("RAY_TPU_WORKER_POOL_WARM_TARGET", "0")
        assert agent.WARM_TARGET == 4  # max(2, num_cpus)
        assert agent.warm_lease_enabled
        monkeypatch.setenv("RAY_TPU_WORKER_POOL_WARM_TARGET", "-1")
        assert agent.WARM_TARGET == 0
        assert not agent.warm_lease_enabled
        live = _registered_handle(pid=os.getpid())
        agent.idle_workers.append(live)
        assert agent._lease_warm_worker() is None  # disabled: cold path

    def test_batch_hist_buckets(self):
        hist = {}
        for n in (1, 2, 3, 8, 64, 129, 500):
            _note_hist(hist, n)
        assert hist == {"1": 1, "2": 1, "4": 1, "8": 1, "64": 1, "128+": 2}


# ---------------------------------------------------------------------------
# unit: O(1) scheduler state
# ---------------------------------------------------------------------------
class TestSchedulerState:
    def test_node_rank_orders_and_updates(self):
        rank = _NodeRank()
        rank.update("a", 0.5)
        rank.update("b", 0.1)
        rank.update("c", 0.9)
        assert rank.ordered_ids() == ["b", "a", "c"]
        rank.update("c", 0.0)  # re-rank on resource report
        assert rank.ordered_ids() == ["c", "b", "a"]
        rank.remove("b")
        assert rank.ordered_ids() == ["c", "a"]
        assert "b" not in rank and "a" in rank
        rank.remove("b")  # idempotent
        assert len(rank) == 2

    def test_state_counts_and_committed_ledger(self, tmp_path):
        head = HeadServer(str(tmp_path), port=0)
        conn = _FakeConn()
        reply, info, op = head._admit_actor(conn, {
            "actor_id": "a1", "spec": {"resources": {"CPU": 1.0}},
            "name": "", "namespace": "default"})
        assert reply is None and op[0] == "actor_create"
        assert head._actor_state_counts == {ACTOR_PENDING: 1}
        req = ResourceSet({"CPU": 1.0})
        head._actor_set_node(info, "n1")
        head._commit_placement(info, req, "n1")
        assert head._committed_agg["n1"].get("CPU") == 1.0
        assert head._actors_by_node["n1"] == {"a1"}
        # readiness uncommits + re-counts
        head._apply_actor_ready(info, {"addr": {"host": "h", "port": 1},
                                       "pid": 7}, "n1")
        assert head._actor_state_counts == {ACTOR_ALIVE: 1}
        assert "n1" not in head._committed_agg
        # death drops the node bucket
        head._actor_set_state(info, ACTOR_DEAD)
        assert head._actor_state_counts == {ACTOR_DEAD: 1}
        assert "n1" not in head._actors_by_node

    def test_committed_ledger_ages_out(self, tmp_path, monkeypatch):
        head = HeadServer(str(tmp_path), port=0)
        conn = _FakeConn()
        _r, info, _op = head._admit_actor(conn, {
            "actor_id": "a1", "spec": {}, "name": "", "namespace": "d"})
        head._commit_placement(info, ResourceSet({"CPU": 1.0}), "n1")
        # entry older than the window is pruned on the next read
        head._committed_nodes["n1"]["a1"] = (
            time.monotonic() - head.COMMIT_WINDOW_S - 1,
            head._committed_nodes["n1"]["a1"][1])
        head._prune_committed("n1")
        assert "n1" not in head._committed_agg


# ---------------------------------------------------------------------------
# unit: batched framing round trip (HeadServer with fake conns)
# ---------------------------------------------------------------------------
class TestBatchedFraming:
    def test_create_and_ready_batch_round_trip(self, tmp_path):
        head = HeadServer(str(tmp_path), port=0)
        agent_conn = _FakeConn()

        async def run():
            from ray_tpu._private.gcs import NodeInfo
            from ray_tpu._private.resources import NodeResources

            node = NodeInfo("n1", {"host": "127.0.0.1", "port": 1},
                            NodeResources(ResourceSet({"CPU": 8.0})),
                            agent_conn)
            head.nodes["n1"] = node
            head._rank_update(node)
            driver = _FakeConn()
            items = [{"actor_id": f"a{i}",
                      "spec": {"resources": {"CPU": 0.01}},
                      "name": "", "namespace": "default"}
                     for i in range(5)]
            reply = await head._create_actor_batch(driver, {"items": items})
            assert [r["state"] for r in reply["results"]] == \
                [ACTOR_PENDING] * 5
            # one StartActorBatch frame, all five entries, to the node
            methods = [m for m, _ in agent_conn.pushes]
            assert methods.count("StartActorBatch") == 1
            batch = agent_conn.pushes[-1][1]["items"]
            assert {it["actor_id"] for it in batch} == \
                {f"a{i}" for i in range(5)}
            # duplicate delivery adopts instead of double-creating
            dup = await head._create_actor_batch(driver, {"items": items})
            assert all(r["state"] == ACTOR_PENDING
                       for r in dup["results"])
            assert len(head.actors) == 5
            # readiness batch flips every entry ALIVE in one call
            agent_conn.meta["node_id"] = "n1"
            ready = await head._actor_ready_batch(agent_conn, {
                "items": [{"actor_id": f"a{i}",
                           "addr": {"host": "h", "port": 2 + i},
                           "pid": 100 + i} for i in range(5)]})
            assert ready["n"] == 5
            assert head._actor_state_counts == {ACTOR_ALIVE: 5}
            assert all(head.actors[f"a{i}"].addr["port"] == 2 + i
                       for i in range(5))
            # per-entry blast radius: a taken name fails only its entry
            await head._create_actor(driver, {
                "actor_id": "named1", "spec": {}, "name": "dup",
                "namespace": "default"})
            mixed = await head._create_actor_batch(driver, {"items": [
                {"actor_id": "named2", "spec": {}, "name": "dup",
                 "namespace": "default"},
                {"actor_id": "b1", "spec": {}, "name": "",
                 "namespace": "default"},
            ]})
            assert "error" in mixed["results"][0]
            assert mixed["results"][1]["state"] == ACTOR_PENDING

        asyncio.run(run())


# ---------------------------------------------------------------------------
# integration: warm-pool burst + chaos
# ---------------------------------------------------------------------------
@pytest.fixture
def warm_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKER_POOL_WARM_TARGET", "8")
    monkeypatch.setenv("RAY_TPU_WORKER_POOL_REFILL_INTERVAL_MS", "20")
    # a creation burst on this 2-core box can starve the agent loop of
    # CPU past the default 15s heartbeat budget (the node is BUSY, not
    # dead); these tests assert pool mechanics, not box timing
    monkeypatch.setenv("RAY_TPU_HEALTH_CHECK_FAILURE_THRESHOLD", "40")
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _pool_stats():
    from ray_tpu._private import worker as wm

    w = wm.global_worker
    return w._acall(w.agent.call("GetWorkerPoolStats", {}, timeout=10),
                    timeout=15)


def _wait_warm(n, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = _pool_stats()
        if st["warm"] >= n:
            return st
        time.sleep(0.2)
    raise AssertionError(f"warm pool never reached {n}: {_pool_stats()}")


@ray_tpu.remote
class Probe:
    def __init__(self):
        import sys

        # recorded BEFORE any user import could pull jax in: a parked
        # warm worker pre-touching JAX/TPU state would break the
        # MULTICHIP dryrun gate's device ownership
        self.jax_preimported = "jax" in sys.modules

    def ping(self):
        return 1

    def jax_was_preimported(self):
        return self.jax_preimported

    def pid(self):
        return os.getpid()


class TestWarmPoolCluster:
    def test_burst_rides_pool_and_batches(self, warm_cluster):
        _wait_warm(4)
        before = _pool_stats()
        n = 100
        actors = [Probe.options(num_cpus=0.001).remote() for _ in range(n)]
        assert ray_tpu.get([a.ping.remote() for a in actors],
                           timeout=600) == [1] * n
        after = _pool_stats()
        hits = after["hits"] - before["hits"]
        # the pool serves the front of the burst + refills along the way
        assert hits >= 8, f"expected warm hits, got {after}"
        # readiness rode coalesced frames: at least one multi-entry batch
        multi = sum(v for k, v in after["ready_batch_hist"].items()
                    if k not in ("1",))
        assert multi >= 1, after["ready_batch_hist"]
        for a in actors:
            ray_tpu.kill(a)

    def test_warm_worker_never_imports_jax(self, warm_cluster):
        from ray_tpu._private.shm_rpc import SHM_STATS

        _wait_warm(2)
        before = _pool_stats()
        shm_before = SHM_STATS["calls_out"]
        probe = Probe.options(num_cpus=0.001).remote()
        assert ray_tpu.get(probe.jax_was_preimported.remote(),
                           timeout=120) is False
        after = _pool_stats()
        assert after["hits"] > before["hits"], \
            "probe was expected to ride a warm worker"
        # the new direct-call paths keep the gate contract too: the
        # probe's calls rode the shm lane (same node) and the parked
        # worker STILL never touched jax (mux/shm_rpc import none)
        assert SHM_STATS["calls_out"] > shm_before, \
            "same-node probe call did not ride the shm lane"

        # the batched fast path (ISSUE 18) keeps the gate contract too:
        # a map() batch through the warm pool leaves every executing
        # worker jax-free, and the driver's spec-template cache (the
        # fast path's signature memo) was actually exercised
        @ray_tpu.remote(num_cpus=0.001)
        def jax_loaded(i):
            import sys

            return "jax" in sys.modules

        assert ray_tpu.get(jax_loaded.map(range(8)),
                           timeout=120) == [False] * 8
        import ray_tpu._private.worker as _worker_mod

        assert _worker_mod.global_worker._spec_templates, \
            "map() batch did not populate the spec-template cache"
        ray_tpu.kill(probe)

    def test_kill_warm_then_leased_worker(self, warm_cluster):
        """SIGKILL a PARKED warm worker, then a JUST-LEASED one: creation
        falls back to cold forks, nothing hangs, and the pid registry
        converges (conftest leak gate asserts the final sweep)."""
        from ray_tpu._private import lifecycle, worker as wm

        st = _wait_warm(3)
        session_dir = None
        for root in lifecycle.default_session_roots():
            if os.path.isdir(root):
                sessions = sorted(
                    (os.path.join(root, d) for d in os.listdir(root)),
                    key=os.path.getmtime)
                if sessions:
                    session_dir = sessions[-1]
        assert session_dir
        # a parked warm worker = registered role=worker pid hosting no actor
        live = [r for r in lifecycle.live_registered(session_dir)
                if r.get("role") == "worker"]
        assert live, "no registered workers"
        os.kill(live[0]["pid"], signal.SIGKILL)
        time.sleep(0.5)
        # creation still completes (ledger/conn-drop evicts the corpse)
        a = Probe.options(num_cpus=0.001).remote()
        assert ray_tpu.get(a.ping.remote(), timeout=120) == 1
        # now SIGKILL a JUST-LEASED worker (the live actor's pid)
        pid = ray_tpu.get(a.pid.remote(), timeout=60)
        os.kill(pid, signal.SIGKILL)
        # a fresh creation must still work, promptly, with no hang
        b = Probe.options(num_cpus=0.001).remote()
        assert ray_tpu.get(b.ping.remote(), timeout=120) == 1
        ray_tpu.kill(b)
        assert st["warm_target"] == 8
