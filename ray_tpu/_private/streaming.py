"""Streaming generator returns (reference: the core_worker streaming
generator path — ``num_returns="streaming"`` tasks report each yielded
value to the owner as it is produced via ``ReportGeneratorItemReturns``;
``src/ray/core_worker/task_manager.h`` streaming-generator state and
``python/ray/_raylet.pyx`` ObjectRefGenerator).

Owner side: each reported item becomes an owned ObjectRef pushed into a
thread-safe queue; the user thread iterates the ``ObjectRefGenerator``,
blocking until the next item (or task completion) arrives. The executor
awaits the owner's ack per item, which gives natural backpressure — a slow
consumer's owner loop throttles the producer's reporting, not memory.
"""

from __future__ import annotations

import queue
from typing import Optional

_END = object()


class ObjectRefGenerator:
    """Iterates ObjectRefs of a streaming task's yields, in yield order
    (reference: _raylet.pyx ObjectRefGenerator / DynamicObjectRefGenerator).
    """

    def __init__(self, task_id_hex: str):
        self._task_id_hex = task_id_hex
        self._queue: "queue.Queue" = queue.Queue()
        self._num_yielded = 0
        self._done = False
        self._error: Optional[Exception] = None

    # ------------------------------------------------------- owner plumbing
    def _push(self, ref) -> None:
        self._queue.put(ref)

    def _finish(self, error: Optional[Exception] = None) -> None:
        self._error = error
        self._queue.put(_END)

    # --------------------------------------------------------- user surface
    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self):
        return self._next_internal(timeout=None)

    def _next_internal(self, timeout: Optional[float]):
        if self._done:
            raise StopIteration
        try:
            item = self._queue.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no streaming item within {timeout}s "
                f"(task {self._task_id_hex})")
        if item is _END:
            self._done = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        self._num_yielded += 1
        return item

    def next_with_timeout(self, timeout: float):
        """Next ref, raising TimeoutError if none arrives in time."""
        return self._next_internal(timeout=timeout)

    @property
    def task_id_hex(self) -> str:
        return self._task_id_hex

    def completed(self) -> bool:
        return self._done

    def __repr__(self):
        return (f"ObjectRefGenerator(task={self._task_id_hex}, "
                f"yielded={self._num_yielded}, done={self._done})")


# Reference exposes this alias for dynamic generators.
DynamicObjectRefGenerator = ObjectRefGenerator
