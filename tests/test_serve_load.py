"""Serving-plane load tests (ISSUE 6): bounded admission queues + typed
BackPressureError shed, continuous-batching engine join/leave correctness,
queue-depth autoscaling up/drain-down, replica-kill-mid-stream, and the
@serve.batch per-instance queue keying (weak, no id-reuse mixing).

Reference analog: python/ray/serve/tests/test_backpressure.py +
test_autoscaling_policy.py, scaled to the in-repo control plane.
"""

import gc
import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.exceptions import BackPressureError
from ray_tpu.serve._private.engine import ContinuousBatchingEngine


# ---------------------------------------------------------------------------
# Engine unit tests (no cluster)
# ---------------------------------------------------------------------------
def _mk_prefill():
    def prefill(payload, model_id):
        return {"tag": payload["tag"], "n": int(payload["n"]), "i": 0}

    return prefill


def _mk_step(delay=0.0, gate=None, seen=None):
    def step(model_id, states):
        if gate is not None:
            gate.wait(timeout=30)
        if delay:
            time.sleep(delay)
        if seen is not None:
            seen.append((model_id,
                         sum(1 for s in states if s is not None),
                         len(states)))
        results = [None] * len(states)
        for i, s in enumerate(states):
            if s is None:
                continue
            s["i"] += 1
            results[i] = (f"{s['tag']}{s['i']}", s["i"] >= s["n"])
        return results

    return step


def _collect(engine, payload, model_id="", out=None, idx=None):
    toks = list(engine.submit(payload, model_id))
    if out is not None:
        out[idx] = toks
    return toks


def test_engine_single_request():
    eng = ContinuousBatchingEngine(
        _mk_step(), prefill_fn=_mk_prefill(), max_batch_size=4,
        idle_timeout_s=0.1, name="single")
    assert _collect(eng, {"tag": "a", "n": 3}) == ["a1", "a2", "a3"]
    eng.shutdown()


def test_engine_join_leave_interleaved():
    """Short generations join a running batch at step boundaries and leave
    when done — they must NOT wait for the long one, and every request
    gets exactly its own tokens."""
    eng = ContinuousBatchingEngine(
        _mk_step(delay=0.01), prefill_fn=_mk_prefill(), max_batch_size=4,
        idle_timeout_s=0.2, name="interleave")
    done_at = {}
    out = {}

    def run(idx, tag, n):
        out[idx] = list(eng.submit({"tag": tag, "n": n}))
        done_at[idx] = time.monotonic()

    threads = [threading.Thread(target=run, args=(0, "L", 40))]
    threads[0].start()
    time.sleep(0.05)  # long one is mid-flight; shorts join its batch
    for i, tag in ((1, "s"), (2, "t"), (3, "u")):
        threads.append(threading.Thread(target=run, args=(i, tag, 3)))
        threads[-1].start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "engine request hung"
    assert out[0] == [f"L{i}" for i in range(1, 41)]
    for i, tag in ((1, "s"), (2, "t"), (3, "u")):
        assert out[i] == [f"{tag}1", f"{tag}2", f"{tag}3"]
        assert done_at[i] < done_at[0], \
            "short generation waited for the long one (no iteration-level " \
            "leave)"
    stats = eng.stats()
    assert stats["max_batch"] > 1, "requests never shared a batch"
    assert stats["completed"] == 4
    eng.shutdown()


def test_engine_bucketed_batch_sizes():
    seen = []
    eng = ContinuousBatchingEngine(
        _mk_step(seen=seen), prefill_fn=_mk_prefill(), max_batch_size=4,
        allowed_batch_sizes=(2, 4), idle_timeout_s=0.2, name="buckets")
    assert eng.bucket_for(1) == 2
    assert eng.bucket_for(3) == 4
    assert eng.bucket_for(4) == 4
    out = {}
    threads = [threading.Thread(target=_collect,
                                args=(eng, {"tag": f"r{i}", "n": 6}, "",
                                      out, i))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for i in range(3):
        assert out[i] == [f"r{i}{j}" for j in range(1, 7)]
    # every dispatched step was padded to an allowed bucket
    assert seen, "no steps recorded"
    for _mid, _live, padded in seen:
        assert padded in (2, 4), f"step ran at non-bucket width {padded}"
    assert eng.stats()["padded_slots"] > 0
    eng.shutdown()


def test_engine_multi_adapter_grouping():
    """Multiplexed requests are grouped per adapter: every step runs a
    single model_id, and all adapters make progress (round-robin)."""
    seen = []
    eng = ContinuousBatchingEngine(
        _mk_step(seen=seen), prefill_fn=_mk_prefill(), max_batch_size=4,
        idle_timeout_s=0.2, name="adapters")
    out = {}
    threads = []
    for i in range(4):
        mid = f"adapter-{i % 2}"
        t = threading.Thread(target=_collect,
                             args=(eng, {"tag": f"x{i}", "n": 5}, mid,
                                   out, i))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=60)
    for i in range(4):
        assert out[i] == [f"x{i}{j}" for j in range(1, 6)]
    mids = {m for m, _, _ in seen}
    assert mids == {"adapter-0", "adapter-1"}, f"adapters seen: {mids}"
    eng.shutdown()


def test_engine_backpressure_shed():
    gate = threading.Event()
    eng = ContinuousBatchingEngine(
        _mk_step(gate=gate), prefill_fn=_mk_prefill(), max_batch_size=2,
        max_pending=2, idle_timeout_s=0.2, name="shed")
    out = {}
    threads = [threading.Thread(target=_collect,
                                args=(eng, {"tag": f"b{i}", "n": 2}, "",
                                      out, i))
               for i in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10
    while eng.stats()["running"] + eng.stats()["pending"] < 2 and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(BackPressureError) as ei:
        eng.submit({"tag": "nope", "n": 1})
    assert eng.stats()["shed"] == 1
    assert ei.value.queue_depths  # carries the observed depth
    gate.set()
    for t in threads:
        t.join(timeout=30)
    assert out[0] == ["b01", "b02"] and out[1] == ["b11", "b12"]
    eng.shutdown()


def test_engine_step_error_propagates():
    def bad_step(model_id, states):
        raise ValueError("boom in step")

    eng = ContinuousBatchingEngine(
        bad_step, prefill_fn=_mk_prefill(), idle_timeout_s=0.1, name="err")
    with pytest.raises(ValueError, match="boom in step"):
        list(eng.submit({"tag": "z", "n": 2}))
    eng.shutdown()


def test_engine_shutdown_mid_generation_no_hang():
    eng = ContinuousBatchingEngine(
        _mk_step(delay=0.02), prefill_fn=_mk_prefill(),
        idle_timeout_s=0.2, name="mid-shutdown")
    caught = {}

    def run():
        try:
            list(eng.submit({"tag": "w", "n": 10_000}))
        except RuntimeError as e:
            caught["err"] = e

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.15)  # generation is mid-flight
    eng.shutdown()
    t.join(timeout=30)
    assert not t.is_alive(), "consumer hung through engine shutdown"
    assert "shut down" in str(caught.get("err"))


def test_engine_idle_stepper_exits():
    """The background stepper must not outlive its work: an idle engine
    leaves no thread behind (this is what the conftest leak gate checks
    at session end)."""
    from ray_tpu.serve._private.engine import live_stepper_threads

    eng = ContinuousBatchingEngine(
        _mk_step(), prefill_fn=_mk_prefill(), idle_timeout_s=0.1,
        name="idle-exit")
    assert _collect(eng, {"tag": "q", "n": 2}) == ["q1", "q2"]
    deadline = time.monotonic() + 5
    while any("idle-exit" in n for n in live_stepper_threads()) and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    assert not any("idle-exit" in n for n in live_stepper_threads()), \
        "stepper thread survived past idle_timeout_s"
    # and it restarts lazily for new work
    assert _collect(eng, {"tag": "r", "n": 1}) == ["r1"]
    eng.shutdown()


# ---------------------------------------------------------------------------
# @serve.batch per-instance queue keying (satellite: WeakKeyDictionary)
# ---------------------------------------------------------------------------
def test_batch_queues_not_shared_across_instances():
    import asyncio

    class Tagged:
        def __init__(self, tag):
            self.tag = tag

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
        async def predict(self, items):
            return [f"{self.tag}:{it}" for it in items]

    async def go():
        a, b = Tagged("A"), Tagged("B")
        results = await asyncio.gather(
            *[a.predict(i) for i in range(4)],
            *[b.predict(i) for i in range(4)])
        return results

    results = asyncio.run(go())
    assert results[:4] == [f"A:{i}" for i in range(4)]
    assert results[4:] == [f"B:{i}" for i in range(4)]


def test_batch_queue_evicted_on_gc():
    """id(owner) keying never evicted → a GC'd instance's reused id could
    mix two instances' batches; weak keying evicts with the owner."""
    import asyncio

    from ray_tpu.serve.batching import _owner_queues

    class M:
        @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.01)
        async def f(self, items):
            return items

    m = M()
    assert asyncio.run(m.f(7)) == 7
    assert any(k is m for k in list(_owner_queues.keys()))
    del m
    gc.collect()
    assert not any(isinstance(k, M) for k in list(_owner_queues.keys())), \
        "batch queue kept its dead owner alive / was never evicted"


def test_batch_decorated_class_is_cloudpickleable():
    """Deployment classes travel to replicas via cloudpickle; the batching
    machinery must not hide unpicklable state in the wrapper."""
    import asyncio

    import cloudpickle

    class P:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.01)
        async def f(self, items):
            return [i + 1 for i in items]

    P2 = cloudpickle.loads(cloudpickle.dumps(P))

    async def go():
        p = P2()
        return await asyncio.gather(*[p.f(i) for i in range(3)])

    assert asyncio.run(go()) == [1, 2, 3]


# ---------------------------------------------------------------------------
# Cluster tests: admission queues, autoscaling, chaos
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def serve_cluster():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=8)
    serve.start(http_options={"port": 0})
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_admission_queue_and_typed_shed(serve_cluster):
    """1 executing + 2 queued fit; everything beyond sheds with a typed
    BackPressureError (no spin-retry, no unbounded queue)."""

    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=2)
    class Slow:
        def __call__(self, x):
            time.sleep(2.0)
            return x * 2

    handle = serve.run(Slow.bind(), name="slow", route_prefix="/slow")
    t0 = time.monotonic()
    responses = [handle.remote(i) for i in range(6)]
    ok, shed = [], []
    for r in responses:
        try:
            ok.append(r.result(timeout_s=60))
        except BackPressureError as e:
            shed.append(e)
            # sheds must be FAST typed errors, not spin-retries burning
            # the deadline
            assert time.monotonic() - t0 < 30
    assert len(ok) == 3, f"admitted {len(ok)} (want 1 running + 2 queued)"
    assert len(shed) == 3
    assert all(v in {i * 2 for i in range(6)} for v in ok)
    # the controller saw the sheds through the health-probe piggyback
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st = serve.status("slow")["deployments"].get("Slow", {})
        if st.get("shed_total", 0) >= 3:
            break
        time.sleep(0.25)
    assert st.get("shed_total", 0) >= 3, f"sheds not in status: {st}"
    serve.delete("slow")


def test_queue_drains_in_fifo_order(serve_cluster):
    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=8)
    class Seq:
        def __init__(self):
            self.order = []

        def __call__(self, x):
            self.order.append(x)
            time.sleep(0.05)
            return x

        def get_order(self):
            return self.order

    handle = serve.run(Seq.bind(), name="seq", route_prefix="/seq")
    # warm the path, then submit a strictly ordered burst
    handle.remote(-1).result(timeout_s=30)
    responses = []
    for i in range(6):
        responses.append(handle.remote(i))
        time.sleep(0.01)  # give each submit its admission turn
    assert [r.result(timeout_s=60) for r in responses] == list(range(6))
    order = serve.get_deployment_handle(
        "Seq", "seq").get_order.remote().result(timeout_s=30)
    assert order[1:] == sorted(order[1:]), \
        f"queued requests executed out of FIFO order: {order}"
    serve.delete("seq")


def test_autoscale_up_then_drain_down(serve_cluster):
    """Queue-depth-driven autoscaling: sustained load scales past 1
    replica; when the load stops the deployment drains back to
    min_replicas via Replica.drain."""

    @serve.deployment(max_ongoing_requests=2, max_queued_requests=64,
                      autoscaling_config={"min_replicas": 1,
                                          "max_replicas": 3,
                                          "target_ongoing_requests": 1.0,
                                          "upscale_delay_s": 0.5,
                                          "downscale_delay_s": 0.5})
    class Busy:
        def __call__(self, x):
            time.sleep(0.25)
            return x

    handle = serve.run(Busy.bind(), name="busy", route_prefix="/busy")
    stop = threading.Event()
    errors = []

    def client():
        while not stop.is_set():
            try:
                handle.remote(1).result(timeout_s=60)
            except BackPressureError:
                pass  # overload shed is allowed; hangs/other errors not
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 60
        peak = 1
        while time.monotonic() < deadline:
            st = serve.status("busy")["deployments"].get("Busy", {})
            peak = max(peak, st.get("replicas", 1))
            if peak > 1:
                break
            time.sleep(0.25)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert not errors, f"client saw non-backpressure errors: {errors[:3]}"
    assert peak > 1, "deployment never scaled up under sustained load"
    # drain back down to min_replicas
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = serve.status("busy")["deployments"].get("Busy", {})
        if st.get("replicas") == 1 and st.get("target_replicas") == 1:
            break
        time.sleep(0.5)
    assert st.get("replicas") == 1, f"did not drain to min_replicas: {st}"
    serve.delete("busy")


def test_replica_kill_mid_stream_typed_error(serve_cluster):
    """SIGKILL the replica mid-stream: the consumer gets a clean typed
    error (or the stream completes via another replica) — never a hang;
    the deployment recovers for subsequent requests."""

    @serve.deployment(num_replicas=1, max_ongoing_requests=4)
    class Streamer:
        def pid(self):
            return os.getpid()

        def __call__(self, n):
            for i in range(int(n)):
                time.sleep(0.1)
                yield i

    handle = serve.run(Streamer.bind(), name="streamer",
                       route_prefix="/streamer")
    victim = handle.pid.remote().result(timeout_s=30)
    outcome = {}
    got: list = []

    def consume():
        try:
            for chunk in handle.options(stream=True).remote(100):
                got.append(chunk)
        except Exception as e:  # noqa: BLE001 — asserted typed below
            outcome["error"] = e

    t = threading.Thread(target=consume)
    t.start()
    deadline = time.monotonic() + 30
    while not got and time.monotonic() < deadline:
        time.sleep(0.05)  # wait until the stream is flowing
    os.kill(victim, signal.SIGKILL)
    t.join(timeout=60)
    assert not t.is_alive(), "stream consumer hung after replica kill"
    err = outcome.get("error")
    if err is not None:
        from ray_tpu.exceptions import RayTpuError

        assert isinstance(err, (RayTpuError, ConnectionError)), \
            f"untyped error after replica kill: {type(err).__name__}: {err}"
    # the controller replaces the dead replica; new requests succeed
    deadline = time.monotonic() + 90
    recovered = False
    while time.monotonic() < deadline and not recovered:
        try:
            got = list(handle.options(stream=True).remote(3))
            recovered = got == [0, 1, 2]
        except Exception:  # noqa: BLE001 — still recovering
            time.sleep(0.5)
    assert recovered, "deployment did not recover after replica kill"
    serve.delete("streamer")


def test_llama_engine_generation():
    """llm.py wiring: continuously-batched LoRA generation produces the
    right number of tokens per request and distinct adapters generate
    distinct sequences (in-process, no cluster — replica hosting is
    covered by the cluster tests above)."""
    from ray_tpu.serve.llm import LlamaGenerator

    gen = LlamaGenerator(config="debug_1l", lora_rank=2,
                         max_batch_size=2, allowed_batch_sizes=(1, 2),
                         max_new_tokens=4, seq_bucket=16)
    try:
        out = {}
        threads = []
        for i, adapter in enumerate(("", "a1", "a2", "a1")):
            def run(idx=i, ad=adapter):
                out[idx] = list(gen({"prompt": [3, 5, 7], "max_new": 4,
                                     "adapter": ad}))

            t = threading.Thread(target=run)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "llama generation hung"
        for i in range(4):
            assert len(out[i]) == 4, f"request {i}: {out[i]}"
            assert all(isinstance(t, int) for t in out[i])
        # same adapter + same prompt → identical (greedy); the two a1
        # requests joined different batches, so this also checks padding
        # doesn't leak across rows
        assert out[1] == out[3], "same adapter diverged across batches"
        stats = gen.engine.stats()
        assert stats["completed"] == 4
    finally:
        gen.engine.shutdown()
