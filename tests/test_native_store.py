"""C++ shared-memory arena store tests (ray_tpu/_native/store.cc — the
plasma analog; reference test parity: the C++ plasma unit tests under
src/ray/object_manager/plasma/ and python/ray/tests/test_object_store*.py).
"""

import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from ray_tpu._native import NativeStore, build_native_lib

pytestmark = pytest.mark.skipif(
    build_native_lib() is None, reason="native toolchain unavailable")


@pytest.fixture
def store(tmp_path):
    path = str(tmp_path / "segment")
    s = NativeStore(path, capacity=1 << 20, create=True)
    yield s


def _oid():
    return os.urandom(20)


class TestLifecycle:
    def test_create_seal_get(self, store):
        oid = _oid()
        v = store.create(oid, 5)
        v[:5] = b"abcde"
        assert not store.contains(oid)  # unsealed: invisible to readers
        assert store.seal(oid)
        assert store.contains(oid)
        r = store.get(oid)
        assert bytes(r[:5]) == b"abcde"
        store.release(oid)

    def test_get_missing(self, store):
        assert store.get(_oid()) is None

    def test_duplicate_create_fails(self, store):
        oid = _oid()
        assert store.create(oid, 4) is not None
        assert store.create(oid, 4) is None

    def test_abort(self, store):
        oid = _oid()
        store.create(oid, 4)
        assert store.abort(oid)
        # id is reusable after abort
        v = store.create(oid, 4)
        assert v is not None

    def test_delete_and_reuse(self, store):
        oid = _oid()
        v = store.create(oid, 4)
        v[:4] = b"1234"
        store.seal(oid)
        assert store.delete(oid)
        assert not store.contains(oid)
        v2 = store.create(oid, 6)
        v2[:6] = b"567890"
        store.seal(oid)
        assert bytes(store.get(oid)[:6]) == b"567890"
        store.release(oid)

    def test_zero_size_object(self, store):
        oid = _oid()
        store.create(oid, 0)
        store.seal(oid)
        assert store.get(oid) is not None
        store.release(oid)


class TestEviction:
    def test_lru_eviction_under_pressure(self, store):
        ids = []
        for _ in range(40):  # 40 * 50k > 1 MiB capacity
            oid = _oid()
            v = store.create(oid, 50_000)
            assert v is not None
            store.seal(oid)
            ids.append(oid)
        st = store.stats()
        assert st["num_evictions"] > 0
        assert st["used"] <= st["capacity"]
        # oldest objects evicted, newest survive
        assert store.contains(ids[-1])
        assert not store.contains(ids[0])

    def test_pinned_objects_survive(self, store):
        pinned = _oid()
        v = store.create(pinned, 50_000)
        store.seal(pinned)
        view = store.get(pinned)  # pin
        for _ in range(40):
            oid = _oid()
            if store.create(oid, 50_000) is not None:
                store.seal(oid)
        assert store.contains(pinned)
        assert view is not None
        store.release(pinned)

    def test_oversize_object_rejected(self, store):
        assert store.create(_oid(), 2 << 20) is None

    def test_lru_candidates_ordering(self, store):
        a, b = _oid(), _oid()
        for oid in (a, b):
            store.create(oid, 100)
            store.seal(oid)
        # touch a so b becomes oldest
        store.get(a)
        store.release(a)
        cands = store.lru_candidates(2)
        assert cands[0] == b


class TestCrossProcess:
    def test_child_process_reads(self, tmp_path):
        path = str(tmp_path / "segment")
        s = NativeStore(path, capacity=1 << 20, create=True)
        oid = _oid()
        v = s.create(oid, 8)
        v[:8] = b"crosspro"
        s.seal(oid)
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "from ray_tpu._native import NativeStore\n"
            "s = NativeStore(%r)\n"
            "r = s.get(bytes.fromhex(%r))\n"
            "assert bytes(r[:8]) == b'crosspro'\n"
            "s.release(bytes.fromhex(%r))\n"
        ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
             path, oid.hex(), oid.hex())
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=60)
        assert res.returncode == 0, res.stderr

    def test_child_process_writes(self, tmp_path):
        path = str(tmp_path / "segment")
        s = NativeStore(path, capacity=1 << 20, create=True)
        oid = _oid()
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "from ray_tpu._native import NativeStore\n"
            "s = NativeStore(%r)\n"
            "oid = bytes.fromhex(%r)\n"
            "v = s.create(oid, 4); v[:4] = b'wxyz'; s.seal(oid)\n"
        ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
             path, oid.hex())
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=60)
        assert res.returncode == 0, res.stderr
        r = s.get(oid)
        assert bytes(r[:4]) == b"wxyz"
        s.release(oid)


class TestStoreClientFacade:
    def test_put_get_roundtrip(self, tmp_path):
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_store import NativeStoreClient

        c = NativeStoreClient(str(tmp_path / "store"), capacity=1 << 20)
        oid = ObjectID.from_random()
        c.put_bytes(oid, b"hello")
        assert c.contains(oid)
        view = c.get_view(oid)
        assert bytes(view[:5]) == b"hello"

    def test_view_pins_until_collected(self, tmp_path):
        """A live view must block eviction; dropping it must unpin."""
        import gc

        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_store import NativeStoreClient

        c = NativeStoreClient(str(tmp_path / "store"), capacity=1 << 20)
        oid = ObjectID.from_random()
        c.put_bytes(oid, b"x" * 100_000)
        view = c.get_view(oid)
        arr = np.frombuffer(view, dtype=np.uint8)  # alias, like deserialize
        # pressure: 15 * 100k > 1 MiB, but the pinned object must survive
        for _ in range(15):
            c.put_bytes(ObjectID.from_random(), b"y" * 100_000)
        assert c.contains(oid)
        assert arr[0] == ord("x")
        del arr, view
        gc.collect()
        # unpinned now: further pressure evicts it
        for _ in range(15):
            c.put_bytes(ObjectID.from_random(), b"z" * 100_000)
        assert not c.contains(oid)


class TestEndToEndNativeBackend:
    def test_task_roundtrip_with_native_store(self, tmp_path):
        """Full init/remote/get with RAY_TPU_STORE_BACKEND=native, in a
        subprocess so the env var reaches every spawned worker."""
        code = """
import sys, numpy as np
sys.path.insert(0, %r)
import ray_tpu

ray_tpu.init(num_cpus=2)

@ray_tpu.remote
def big(x):
    return np.full((1 << 16,), x, dtype=np.float32)

refs = [big.remote(i) for i in range(4)]
out = ray_tpu.get(refs)
for i, a in enumerate(out):
    assert a.shape == (1 << 16,) and float(a[0]) == float(i)
ray_tpu.shutdown()
print("E2E_OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["RAY_TPU_STORE_BACKEND"] = "native"
        env["JAX_PLATFORMS"] = "cpu"
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=300,
                             env=env)
        assert "E2E_OK" in res.stdout, res.stdout + res.stderr


def test_ids_differing_only_in_last_4_bytes_do_not_collide(tmp_path):
    """ObjectIDs are task_id(16B) + return index(4B); puts from one worker
    share their first 16 bytes — the store must key on all 20."""
    s = NativeStore(str(tmp_path / "segment"), capacity=1 << 20, create=True)
    base = os.urandom(16)
    ids = [base + i.to_bytes(4, "little") for i in range(4)]
    for i, oid in enumerate(ids):
        v = s.create(oid, 4)
        assert v is not None, f"create {i} collided"
        v[:4] = bytes([i]) * 4
        s.seal(oid)
    for i, oid in enumerate(ids):
        r = s.get(oid)
        assert bytes(r[:4]) == bytes([i]) * 4
        s.release(oid)


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
class TestHardening:
    """VERDICT r3 next #8: EOWNERDEAD robust-mutex recovery, multi-writer
    stress, and the ASAN build target — run as a native unit binary (the
    reference's plasma test culture, src/ray/object_manager/plasma/)."""

    def test_asan_unit_binary(self, tmp_path):
        import ray_tpu._native as native

        src = os.path.join(os.path.dirname(native.__file__),
                           "store_test.cc")
        binary = str(tmp_path / "store_test")
        subprocess.run(
            ["g++", "-std=c++17", "-g", "-fsanitize=address,undefined",
             "-o", binary, src, "-lpthread"],
            check=True, capture_output=True, timeout=300)
        out = subprocess.run(
            [binary, str(tmp_path / "seg")], capture_output=True,
            text=True, timeout=300)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "store_test OK" in out.stdout

    def test_eownerdead_recovery_from_python(self, tmp_path):
        """A ctypes client killed while HOLDING the segment mutex (with a
        half-written object) must not wedge other clients: the next op
        recovers the robust mutex and sweeps the orphaned slot."""
        import ray_tpu._native as native

        if native.get_native_lib() is None:
            pytest.skip("native lib unavailable")
        seg = str(tmp_path / "seg")
        store = NativeStore(seg, capacity=1 << 20, create=True)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = (
            "from ray_tpu._native import NativeStore, get_native_lib\n"
            f"h = NativeStore({seg!r})\n"
            "h.create(b'7' * 20, 2048)\n"  # CREATED, never sealed
            "get_native_lib().tpu_store_test_lock_and_leak(h._h)\n"
            "import os; os._exit(0)\n"
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        buf = store.create(b"8" * 20, 1024)
        assert buf is not None
        assert store.seal(b"8" * 20)
        assert not store.contains(b"7" * 20)

    def test_multiprocess_writer_stress_python(self, tmp_path):
        """4 concurrent ctypes writers hammering one segment; the arena
        stays consistent and usable."""
        import ray_tpu._native as native

        if native.get_native_lib() is None:
            pytest.skip("native lib unavailable")
        seg = str(tmp_path / "seg")
        store = NativeStore(seg, capacity=4 << 20, create=True)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        code = (
            "import os, random, sys\n"
            "from ray_tpu._native import NativeStore\n"
            f"h = NativeStore({seg!r})\n"
            "rng = random.Random(int(sys.argv[1]))\n"
            "for _ in range(2000):\n"
            "    oid = bytes([rng.randrange(64)]) * 20\n"
            "    op = rng.randrange(3)\n"
            "    if op == 0:\n"
            "        if h.create(oid, 1 + rng.randrange(8192)) is not None:\n"
            "            h.seal(oid)\n"
            "    elif op == 1:\n"
            "        if h.get(oid) is not None:\n"
            "            h.release(oid)\n"
            "    else:\n"
            "        h.delete(oid)\n"
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        procs = [subprocess.Popen([sys.executable, "-c", code, str(i)],
                                  env=env, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True)
                 for i in range(4)]
        for p in procs:
            out, _ = p.communicate(timeout=240)
            assert p.returncode == 0, out
        buf = store.create(b"z" * 20, 4096)
        assert buf is not None and store.seal(b"z" * 20)
        stats = store.stats()
        assert stats["num_objects"] >= 1
