"""Streaming generator returns (reference: core_worker streaming
generators — num_returns='streaming', ReportGeneratorItemReturns,
ObjectRefGenerator in _raylet.pyx)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray2():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_streaming_task_yields_refs_in_order(ray2):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    vals = [ray_tpu.get(r, timeout=60) for r in g]
    assert vals == [0, 10, 20, 30, 40]
    assert g.completed()


def test_streaming_items_arrive_before_task_finishes(ray2):
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            time.sleep(0.3)
            yield i

    g = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(iter(g)), timeout=60)
    first_at = time.monotonic() - t0
    rest = [ray_tpu.get(r, timeout=60) for r in g]
    total = time.monotonic() - t0
    assert first == 0 and rest == [1, 2, 3]
    # first item must land well before the generator drains
    assert first_at < total - 0.5, (first_at, total)


def test_streaming_large_objects_via_store(ray2):
    @ray_tpu.remote(num_returns="streaming")
    def bigs():
        for i in range(3):
            yield np.full(300_000, i, np.float64)

    arrays = [ray_tpu.get(r, timeout=60) for r in bigs.remote()]
    assert [int(a[0]) for a in arrays] == [0, 1, 2]
    assert arrays[0].shape == (300_000,)


def test_streaming_midway_exception_is_next_ref(ray2):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        raise ValueError("boom")

    refs = list(bad.remote())
    assert len(refs) == 2
    assert ray_tpu.get(refs[0], timeout=60) == 1
    with pytest.raises(Exception, match="boom"):
        ray_tpu.get(refs[1], timeout=60)


def test_streaming_actor_method(ray2):
    @ray_tpu.remote
    class Streamer:
        def counts(self, n):
            for i in range(n):
                yield i

    s = Streamer.remote()
    got = [ray_tpu.get(r, timeout=60) for r in
           s.counts.options(num_returns="streaming").remote(4)]
    assert got == [0, 1, 2, 3]


def test_early_ref_free_does_not_break_stream(ray2):
    """Dropping consumed refs (the normal consumption pattern) must not
    tear down the in-flight stream's task record."""
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(20):
            yield i

    total = 0
    for ref in gen.remote():
        total += ray_tpu.get(ref, timeout=60)  # ref freed each iteration
    assert total == sum(range(20))


def test_num_returns_validation():
    with pytest.raises(ValueError):
        @ray_tpu.remote(num_returns="bogus")
        def f():
            pass
