"""Bin-packing of unfulfilled resource demands onto node types
(reference: python/ray/autoscaler/_private/resource_demand_scheduler.py).

Given the live cluster view and a list of pending resource requests, decide
how many nodes of each type to launch. First-fit-decreasing over demands,
respecting per-type max_workers and the global max.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ray_tpu._private.resources import ResourceSet


def _native_pack(node_types, demands, existing_available, existing_counts,
                 max_workers, total_workers):
    """C++ bin-packing fast path (ray_tpu/_native/sched.cc); None when the
    native kernel is unavailable. The caller pre-sorts demands so both
    paths place in the same order."""
    import os

    if os.environ.get("RAY_TPU_NATIVE_SCHED", "1") == "0":
        return None
    try:
        from ray_tpu._native import NativeScheduler

        sched = NativeScheduler()
    except Exception:
        return None
    # everything in fixed-point wire units (demands/pools already are)
    return sched.bin_pack(
        list(demands), list(existing_available),
        {t: {"resources": ResourceSet(
                 dict(spec.get("resources", {}))).to_wire(),
             "max_workers": spec.get("max_workers", max_workers)}
         for t, spec in node_types.items()},
        max_workers, total_workers, dict(existing_counts))


def _fit_on(demand: ResourceSet, pools: List[ResourceSet]) -> bool:
    """Try to place `demand` on one of `pools` (mutating the winner)."""
    for pool in pools:
        if demand.fits(pool):
            pool.subtract(demand)
            return True
    return False


def get_nodes_to_launch(
    node_types: Dict[str, Dict],
    demands: List[Dict[str, int]],
    existing_available: List[Dict[str, int]],
    existing_counts: Dict[str, int],
    max_workers: int,
    total_workers: int,
) -> Dict[str, int]:
    """Returns {node_type: count} to launch.

    node_types: {name: {"resources": {...}, "max_workers": int}}
    demands: wire-format ResourceSets of queued lease requests
    existing_available: wire-format available pools of alive nodes
    existing_counts: current worker count per type
    """
    # FFD ordering decided ONCE here so the native kernel and the Python
    # fallback see identical demand order and make identical decisions
    demands = sorted(demands, key=lambda w: -sum(w.values()))
    multi_host = any("per_host_resources" in spec
                     or "_per_host_resources" in spec
                     for spec in node_types.values())
    # the native kernel packs against aggregate capacity only; slice types
    # need the per-host feasibility guard below, so they take the Python path
    native = None if multi_host else _native_pack(
        node_types, demands, existing_available,
        existing_counts, max_workers, total_workers)
    if native is not None:
        return native
    pools = [ResourceSet.from_wire(w) for w in existing_available]
    unfulfilled: List[ResourceSet] = []
    for wire in demands:
        demand = ResourceSet.from_wire(wire)
        if not _fit_on(demand, pools):
            unfulfilled.append(demand)
    if not unfulfilled:
        return {}

    to_launch: Dict[str, int] = {}
    counts = dict(existing_counts)
    budget = max(0, max_workers - total_workers)
    new_pools: List[ResourceSet] = []
    for demand in unfulfilled:
        if _fit_on(demand, new_pools):
            continue
        chosen = None
        for name, spec in node_types.items():
            cap = ResourceSet(dict(spec.get("resources", {})))
            if not demand.feasible_on(cap):
                continue
            # multi-host types (TPU slices): "resources" is the slice
            # aggregate, but one demand must fit on ONE host — launching a
            # slice no host of which can run the request would churn
            # useless slices forever
            per_host = spec.get("per_host_resources")                 or spec.get("_per_host_resources")
            if per_host is not None and not demand.feasible_on(
                    ResourceSet(dict(per_host))):
                continue
            if counts.get(name, 0) >= spec.get("max_workers", max_workers):
                continue
            chosen = (name, cap)
            break
        if chosen is None or budget <= 0:
            continue  # infeasible or at capacity: demand stays pending
        name, cap = chosen
        cap.subtract(demand)
        new_pools.append(cap)
        to_launch[name] = to_launch.get(name, 0) + 1
        counts[name] = counts.get(name, 0) + 1
        budget -= 1
    return to_launch
