"""Worker-side runtime-env application (the RuntimeEnvContext analog,
reference: python/ray/_private/runtime_env/context.py — which mutates the
worker command; here the worker mutates itself before the first task of a
leased runtime_env executes)."""

from __future__ import annotations

import os
from typing import Dict, Optional

from ray_tpu.runtime_env.plugin import _PLUGINS
from ray_tpu.runtime_env.runtime_env import (
    RuntimeEnvSetupError,
    validate_runtime_env,
)


class RuntimeEnvContext:
    def __init__(self, spec: Dict, cache_root: str):
        self.spec = spec
        self.cache_root = cache_root
        # sys.path entries added by user-code plugins (working_dir,
        # py_modules) this setup; pip venv site-packages slots BELOW these
        # (user code shadows env packages, reference precedence) but above
        # the system site-packages
        self.user_paths: list = []


_applied: Optional[Dict] = None


def setup_runtime_env(spec: Optional[Dict],
                      session_dir: Optional[str] = None) -> None:
    """Apply a runtime_env in this process (idempotent per spec).

    Called by the worker executor before running a task that carries a
    runtime_env. Lease keys pin one runtime_env per leased worker, so a
    changed spec in the same process is a scheduling bug worth surfacing.
    """
    global _applied
    if not spec:
        return
    if _applied is not None:
        if _applied != spec:
            raise RuntimeEnvSetupError(
                "worker already initialized with a different runtime_env "
                f"({_applied} != {spec})")
        return
    validate_runtime_env(spec)
    cache_root = os.path.join(
        session_dir or os.environ.get("RAY_TPU_SESSION_DIR", "/tmp"),
        "runtime_env_cache")
    os.makedirs(cache_root, exist_ok=True)
    context = RuntimeEnvContext(spec, cache_root)
    plugins = [(k, _PLUGINS[k]) for k in spec if k in _PLUGINS]
    plugins.sort(key=lambda kv: kv[1].priority)
    for key, plugin in plugins:
        try:
            plugin.setup(spec[key], context)
        except RuntimeEnvSetupError:
            raise
        except Exception as e:
            raise RuntimeEnvSetupError(
                f"runtime_env field {key!r} setup failed: {e}") from e
    _applied = dict(spec)
