"""Autoscaler v2 — instance state machine + reconciler + status SDK
(reference: python/ray/autoscaler/v2/tests/)."""

import pytest

import ray_tpu
from ray_tpu.autoscaler.v2 import (
    Instance, InstanceManager, Reconciler, get_cluster_status)
from ray_tpu.autoscaler.v2.instance_manager import (
    ALLOCATED, QUEUED, RAY_RUNNING, REQUESTED, TERMINATED, TERMINATING)


class FakeProvider:
    def __init__(self):
        self.nodes = {}
        self._n = 0
        self.joined = {}

    def create_node(self, node_type, count):
        out = []
        for _ in range(count):
            self._n += 1
            cid = f"cloud-{self._n}"
            self.nodes[cid] = node_type
            out.append(cid)
        return out

    def non_terminated_nodes(self):
        return list(self.nodes)

    def terminate_node(self, cid):
        self.nodes.pop(cid, None)

    def runtime_node_id(self, cid):
        return self.joined.get(cid)


def test_instance_lifecycle_and_reconcile():
    provider = FakeProvider()
    mgr = InstanceManager()
    cluster_nodes = []
    rec = Reconciler(mgr, provider, lambda: cluster_nodes)

    mgr.request_instances("worker", 2)
    assert len(mgr.instances(QUEUED)) == 2

    t = rec.reconcile()
    assert t.get("launched") == 2
    # launched instances become ALLOCATED on the next pass (they appear in
    # the provider's live list)
    rec.reconcile()
    assert len(mgr.instances(ALLOCATED)) == 2

    # nodes join the cluster -> RAY_RUNNING
    for inst in mgr.instances(ALLOCATED):
        provider.joined[inst.cloud_instance_id] = \
            "node-" + inst.cloud_instance_id
        cluster_nodes.append("node-" + inst.cloud_instance_id)
    rec.reconcile()
    assert len(mgr.instances(RAY_RUNNING)) == 2

    # terminate one
    victim = mgr.instances(RAY_RUNNING)[0]
    mgr.terminate_instance(victim.instance_id)
    assert victim.status == TERMINATING
    rec.reconcile()
    assert victim.status == TERMINATED
    assert victim.cloud_instance_id not in provider.nodes

    # the other dies underneath us
    other = mgr.instances(RAY_RUNNING)[0]
    provider.nodes.pop(other.cloud_instance_id)
    t = rec.reconcile()
    assert t.get("lost") == 1
    assert other.status == TERMINATED


def test_instance_storage_versioning():
    mgr = InstanceManager()
    (inst,) = mgr.request_instances("worker", 1)
    v0 = inst.version
    inst.transition(REQUESTED)
    assert inst.version == v0 + 1
    # optimistic concurrency: stale version rejected
    clone = Instance(instance_id=inst.instance_id, instance_type="worker")
    assert not mgr.storage.upsert(clone, expected_version=v0)
    assert mgr.storage.upsert(clone, expected_version=inst.version)


def test_get_cluster_status():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=2)
    try:
        st = get_cluster_status()
        assert len(st.active_nodes()) >= 1
        assert st.total_resources.get("CPU") == 2.0
        assert "CPU" in st.available_resources
    finally:
        ray_tpu.shutdown()
