"""R3 — a threading lock held across an ``await`` point.

Invariant: a *thread* lock (``threading.Lock``/``RLock``) must never be
held across an ``await``. The await suspends the coroutine but NOT the
lock: every other task on the loop that touches the same lock now blocks
the loop thread itself, which (unlike a task-level ``asyncio.Lock`` wait)
can never be broken by the loop — the classic single-thread deadlock.
Holding a lock across a suspension also silently extends the critical
section to everything the loop interleaves, the same shape that wedged
the driver in the MemoryStore incident (PR 5) — here the loop *is* the
"other thread".

Detection: inside ``async def`` bodies, any sync ``with`` statement whose
context expression resolves (via the project lock index) to a
``threading.Lock``/``RLock`` and whose body subtree contains an ``Await``.
``async with`` on ``asyncio.Lock`` is the sanctioned alternative.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import FunctionInfo, ProjectIndex
from ..model import ModuleInfo, Violation
from .r2_blocking_in_async import _walk_async_body

RULE_ID = "R3"
SUMMARY = ("threading.Lock/RLock held across an await — blocks the loop "
           "thread for every interleaved task; narrow the critical "
           "section or use asyncio.Lock")


def check_module(mod: ModuleInfo, index: ProjectIndex) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        qn = mod.qualname(node)
        cls = qn.split(".")[0] if "." in qn else None
        fn = FunctionInfo(node.name, qn, mod, node, class_name=cls)
        # _walk_async_body skips nested defs: a nested async def's
        # with-blocks are visited under its OWN AsyncFunctionDef pass,
        # never twice, and awaits inside a nested def defined in the
        # with-body don't execute while the lock is held.
        for sub in _walk_async_body(node):
            if not isinstance(sub, ast.With):
                continue
            lock_name = None
            for item in sub.items:
                kind, name = index.lock_kind(fn, item.context_expr)
                if kind in ("Lock", "RLock"):
                    lock_name = name
                    break
            if lock_name is None:
                continue
            awaits = _awaits_in(sub)
            if awaits:
                out.append(mod.violation(
                    RULE_ID, awaits[0],
                    f"thread lock '{lock_name}' is held across this await "
                    f"in '{qn}' (with-block at line {sub.lineno}); the "
                    f"suspension keeps the lock while other tasks run and "
                    f"any of them touching it deadlocks the loop thread — "
                    f"release before awaiting or use asyncio.Lock"))
    return out


def _awaits_in(with_node: ast.With) -> List[ast.Await]:
    """Awaits lexically inside the with-body, excluding nested defs
    (those suspend whoever CALLS them, not this critical section)."""
    out: List[ast.Await] = []
    stack = list(ast.iter_child_nodes(with_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, ast.Await):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out
