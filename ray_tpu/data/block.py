"""Block format for ray_tpu.data.

A block is the unit of parallelism: one contiguous shard of a Dataset that
flows between operators as an ``ObjectRef``. The reference standardizes on
Arrow tables in plasma (reference: python/ray/data/block.py,
``_internal/arrow_block.py``); we do the same but additionally allow a
"tensor block" — a dict of numpy arrays — as a first-class representation,
because TPU feeding wants contiguous ndarrays that ``jax.device_put`` can
ship to HBM without a columnar decode step.

``BlockAccessor`` dispatches over the two representations.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is in the image
    pa = None

# Block = pyarrow.Table | Dict[str, np.ndarray]
Block = Union["pa.Table", Dict[str, np.ndarray]]


@dataclasses.dataclass
class BlockMetadata:
    """Sidecar stats shipped with every block ref so the executor can
    schedule and account without fetching payloads (reference:
    python/ray/data/block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[Dict[str, str]] = None
    input_files: Optional[List[str]] = None
    exec_time_s: float = 0.0


class BlockAccessor:
    """Uniform view over the two block representations."""

    def __init__(self, block: Block):
        self._block = block
        self._is_arrow = pa is not None and isinstance(block, pa.Table)

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # ------------------------------------------------------------- building
    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Normalize a UDF's output batch into a block."""
        if pa is not None and isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            out: Dict[str, np.ndarray] = {}
            multidim = False
            for k, v in batch.items():
                arr = np.asarray(v)
                out[k] = arr
                if arr.ndim > 1 or arr.dtype == object:
                    multidim = True
            if not out:
                return {}
            n = {len(a) for a in out.values()}
            if len(n) > 1:
                raise ValueError(
                    f"batch columns have mismatched lengths: "
                    f"{ {k: len(v) for k, v in out.items()} }")
            if multidim or pa is None:
                return out
            return pa.table(out)
        try:
            import pandas as pd

            if isinstance(batch, pd.DataFrame):
                return pa.Table.from_pandas(batch, preserve_index=False)
        except ImportError:  # pragma: no cover
            pass
        if isinstance(batch, list):
            return BlockAccessor.rows_to_block(batch)
        if isinstance(batch, np.ndarray):
            return BlockAccessor.batch_to_block({"data": batch})
        raise TypeError(
            f"cannot convert batch of type {type(batch).__name__} to a block "
            "(expected dict of arrays, pyarrow.Table, pandas.DataFrame, "
            "list of rows, or ndarray)")

    @staticmethod
    def rows_to_block(rows: List[Any]) -> Block:
        if not rows:
            return pa.table({}) if pa is not None else {}
        if not isinstance(rows[0], dict):
            rows = [{"item": r} for r in rows]
        cols: Dict[str, list] = {k: [] for k in rows[0]}
        uniform = True
        for r in rows:
            if set(r) != set(cols):
                uniform = False
                break
        if not uniform:
            keys = []
            for r in rows:
                for k in r:
                    if k not in keys:
                        keys.append(k)
            cols = {k: [r.get(k) for r in rows] for k in keys}
        else:
            for r in rows:
                for k, v in r.items():
                    cols[k].append(v)
        # ndarray-valued fields → tensor block
        if any(isinstance(v[0], np.ndarray) for v in cols.values() if len(v)):
            return {k: np.stack(v) if isinstance(v[0], np.ndarray)
                    else np.asarray(v) for k, v in cols.items()}
        if pa is None:  # pragma: no cover
            return {k: np.asarray(v) for k, v in cols.items()}
        try:
            return pa.table(cols)
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
            return {k: np.asarray(v, dtype=object) for k, v in cols.items()}

    # ------------------------------------------------------------- reading
    def num_rows(self) -> int:
        if self._is_arrow:
            return self._block.num_rows
        if not self._block:
            return 0
        return len(next(iter(self._block.values())))

    def size_bytes(self) -> int:
        if self._is_arrow:
            return self._block.nbytes
        return int(sum(a.nbytes if a.dtype != object else len(a) * 64
                       for a in self._block.values()))

    def schema(self) -> Optional[Dict[str, str]]:
        if self._is_arrow:
            return {f.name: str(f.type) for f in self._block.schema}
        return {k: f"{v.dtype}{list(v.shape[1:]) if v.ndim > 1 else ''}"
                for k, v in self._block.items()}

    def column_names(self) -> List[str]:
        if self._is_arrow:
            return self._block.column_names
        return list(self._block.keys())

    def metadata(self, **kw) -> BlockMetadata:
        return BlockMetadata(num_rows=self.num_rows(),
                             size_bytes=self.size_bytes(),
                             schema=self.schema(), **kw)

    # --------------------------------------------------------- conversions
    def to_arrow(self) -> "pa.Table":
        if self._is_arrow:
            return self._block
        return pa.table({k: v.tolist() if v.ndim > 1 else v
                         for k, v in self._block.items()})

    def to_pandas(self):
        import pandas as pd

        if self._is_arrow:
            return self._block.to_pandas()
        return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                             for k, v in self._block.items()})

    def to_numpy_dict(self) -> Dict[str, np.ndarray]:
        if not self._is_arrow:
            return dict(self._block)
        out = {}
        for name in self._block.column_names:
            col = self._block.column(name)
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except (pa.ArrowInvalid, NotImplementedError):
                out[name] = np.asarray(col.to_pylist(), dtype=object)
        return out

    def to_batch(self, batch_format: str = "numpy") -> Any:
        if batch_format in ("numpy", "default"):
            return self.to_numpy_dict()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        if self._is_arrow:
            for batch in self._block.to_batches():
                yield from batch.to_pylist()
        else:
            keys = list(self._block)
            for i in range(self.num_rows()):
                yield {k: self._block[k][i] for k in keys}

    # ------------------------------------------------------------ slicing
    def slice(self, start: int, end: int) -> Block:
        if self._is_arrow:
            return self._block.slice(start, end - start)
        return {k: v[start:end] for k, v in self._block.items()}

    def take_indices(self, idx: np.ndarray) -> Block:
        if self._is_arrow:
            return self._block.take(pa.array(idx))
        return {k: v[idx] for k, v in self._block.items()}

    def select(self, columns: List[str]) -> Block:
        if self._is_arrow:
            return self._block.select(columns)
        return {k: self._block[k] for k in columns}

    def drop(self, columns: List[str]) -> Block:
        keep = [c for c in self.column_names() if c not in columns]
        return self.select(keep)

    def rename(self, mapping: Dict[str, str]) -> Block:
        if self._is_arrow:
            return self._block.rename_columns(
                [mapping.get(c, c) for c in self._block.column_names])
        return {mapping.get(k, k): v for k, v in self._block.items()}

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        nonempty = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
        if not nonempty:
            # keep schema from the first (empty) block if there is one
            return blocks[0] if blocks else (
                pa.table({}) if pa is not None else {})
        blocks = nonempty
        if len(blocks) == 1:
            return blocks[0]
        if all(pa is not None and isinstance(b, pa.Table) for b in blocks):
            return pa.concat_tables(blocks, promote_options="default")
        dicts = [BlockAccessor(b).to_numpy_dict() for b in blocks]
        keys = list(dicts[0])
        return {k: np.concatenate([d[k] for d in dicts]) for k in keys}

    def sort_indices(self, key: Union[str, List[str]],
                     descending: bool = False) -> np.ndarray:
        keys = [key] if isinstance(key, str) else list(key)
        nd = self.to_numpy_dict()
        arrs = [nd[k] for k in reversed(keys)]
        idx = np.lexsort(arrs)
        return idx[::-1] if descending else idx

