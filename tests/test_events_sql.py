"""Structured event log + SQL datasource (reference:
src/ray/util/event.h:41 RAY_EVENT files + dashboard event module;
python/ray/data read_sql read_api.py:1902; VERDICT r1 missing #8/#9)."""

import sqlite3
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(scope="module")
def ray_cluster(ray_start_regular):
    yield


def test_startup_events_recorded(ray_cluster):
    deadline = time.time() + 20
    events = []
    while time.time() < deadline:
        events = state.list_cluster_events()
        if any(e["label"] == "NODE_STARTED" for e in events):
            break
        time.sleep(0.5)
    labels = {e["label"] for e in events}
    assert "NODE_STARTED" in labels, labels
    assert "HEAD_STARTED" in labels, labels
    started = next(e for e in events if e["label"] == "NODE_STARTED")
    assert started["severity"] == "INFO"
    assert started["node_id"]
    assert started["timestamp"] > 0


def test_actor_failure_event_recorded(ray_cluster):
    import os

    @ray_tpu.remote
    class Doomed:
        def boom(self):
            os._exit(1)

        def ping(self):
            return 1

    d = Doomed.remote()
    assert ray_tpu.get(d.ping.remote(), timeout=90) == 1
    try:
        ray_tpu.get(d.boom.remote(), timeout=30)
    except Exception:
        pass
    deadline = time.time() + 30
    failures = []
    while time.time() < deadline:
        failures = state.list_cluster_events(label="ACTOR_FAILURE")
        if failures:
            break
        time.sleep(0.5)
    assert failures, "actor failure never recorded"
    assert failures[-1]["severity"] == "WARNING"

    # severity filter
    errors = state.list_cluster_events(severity="ERROR")
    assert all(e["severity"] == "ERROR" for e in errors)


def test_read_sql_roundtrip(ray_cluster, tmp_path):
    import ray_tpu.data as rdata

    db = str(tmp_path / "demo.sqlite")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE points (id INTEGER, value REAL)")
    conn.executemany("INSERT INTO points VALUES (?, ?)",
                     [(i, i * 0.5) for i in range(100)])
    conn.commit()
    conn.close()

    # ordered query -> windowed parallel read tasks
    src = rdata._ds.SQLDatasource("SELECT * FROM points ORDER BY id",
                                  lambda: sqlite3.connect(db))
    assert len(src.get_read_tasks(4)) == 4  # windowing actually engaged
    ds = rdata.read_sql("SELECT * FROM points ORDER BY id",
                        lambda: sqlite3.connect(db), parallelism=4)
    rows = ds.take_all()
    assert len(rows) == 100
    assert sorted(r["id"] for r in rows) == list(range(100))
    assert rows[0]["value"] == rows[0]["id"] * 0.5

    # unordered query: falls back to one task (stability guard)
    src1 = rdata._ds.SQLDatasource("SELECT * FROM points",
                                   lambda: sqlite3.connect(db))
    assert len(src1.get_read_tasks(4)) == 1

    # pipeline composition on top of the SQL read
    total = rdata.read_sql(
        "SELECT * FROM points WHERE id < 10",
        lambda: sqlite3.connect(db)).map(
            lambda r: {"double": r["value"] * 2}).take_all()
    assert len(total) == 10
