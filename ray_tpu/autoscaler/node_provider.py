"""Node providers (reference: python/ray/autoscaler/node_provider.py ABC and
the fake_multi_node provider python/ray/autoscaler/_private/fake_multi_node/
that 'launches' nodes as local processes for tests).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class NodeProvider:
    """Cloud-agnostic node lifecycle interface. Implementations launch and
    terminate worker nodes of the configured node types."""

    def __init__(self, provider_config: Dict, cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_type: str, count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def internal_ip(self, node_id: str) -> Optional[str]:
        return None

    def runtime_node_id(self, node_id: str) -> Optional[str]:
        """Map a provider node id to the runtime node id it registered as
        (None until the node's agent has come up)."""
        return None

    def runtime_node_ids(self, node_id: str) -> List[str]:
        """All runtime node ids behind one provider node. Multi-host
        providers (TPU pod slices) override this; the autoscaler then
        treats the provider node as one atomic scaling unit."""
        rid = self.runtime_node_id(node_id)
        return [rid] if rid else []

    def expected_runtime_nodes(self, node_id: str) -> int:
        """How many runtime nodes this provider node contributes once
        fully booted (hosts per slice for pod slices)."""
        return 1


class LocalNodeProvider(NodeProvider):
    """Launches worker nodes as local agent processes joining an existing
    head — the fake-multi-node analog used by ``AutoscalingCluster`` and the
    autoscaler tests. Each created node boots a real ``Node`` (agent +
    workers), so scheduling against scaled-up nodes is fully exercised on
    one machine.
    """

    def __init__(self, provider_config: Dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.head_host: str = provider_config["head_host"]
        self.head_port: int = provider_config["head_port"]
        self.session_dir: str = provider_config["session_dir"]
        self.node_types: Dict[str, Dict] = provider_config["node_types"]
        self._nodes: Dict[str, Dict] = {}
        # RLock: provider state reads are reachable from GC context
        # (raylint R1) via the session pools' reap paths
        self._lock = threading.RLock()
        self._counter = 0

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._nodes

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            info = self._nodes.get(node_id)
            return {"node_type": info["type"]} if info else {}

    def create_node(self, node_type: str, count: int) -> List[str]:
        from ray_tpu._private.node import Node

        spec = self.node_types[node_type]
        created = []
        for _ in range(count):
            node = Node(
                head=False,
                head_host=self.head_host,
                head_port=self.head_port,
                resources=dict(spec.get("resources", {})),
                labels=dict(spec.get("labels", {}) or {}),
                session_dir=self.session_dir,
            )
            node.start()
            with self._lock:
                self._counter += 1
                pid = f"{self.cluster_name}-{node_type}-{self._counter}"
                self._nodes[pid] = {"type": node_type, "node": node,
                                    "created": time.time()}
            created.append(pid)
        return created

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._nodes.pop(node_id, None)
        if info:
            info["node"].stop()

    def runtime_node_id(self, node_id: str) -> Optional[str]:
        with self._lock:
            info = self._nodes.get(node_id)
        if not info:
            return None
        return getattr(info["node"], "node_id", None)

    def shutdown(self) -> None:
        for nid in self.non_terminated_nodes():
            self.terminate_node(nid)
