"""Ownership-wide lineage reconstruction (ISSUE 17; reference:
src/ray/core_worker/task_manager.h lineage pinning / max_lineage_bytes and
object_recovery_manager.h chained resubmission).

Covers the lineage contract end to end: ledger refcount + evict-on-cap
units, deterministic-seed replay byte-identity, chained (depth >= 2)
reconstruction where a lost task's *argument* is also lost, the
depth/attempt bounds surfacing :class:`ObjectReconstructionFailedError`,
the put()-no-lineage contract, and a DaemonKiller agent-SIGKILL chaos
run. Cluster tests share one module-scoped head; each test brings its own
side node keyed by a unique resource so replays can't land on a previous
test's replacement node.
"""

import hashlib
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.task_spec import NORMAL_TASK, TaskSpec
from ray_tpu._private.worker import LineageLedger, TaskRecord, _replay_seed
from ray_tpu.cluster_utils import Cluster
from ray_tpu.exceptions import ObjectReconstructionFailedError


# ---------------------------------------------------------------------------
# ledger units (no cluster)
# ---------------------------------------------------------------------------
class _FakeWorker:
    def __init__(self):
        self._tasks = {}
        self.unpinned = []

    def _unpin_args(self, spec):
        self.unpinned.append(spec.task_id)


def _spec(task_id: bytes, blob: bytes = b"", max_retries: int = 3) -> TaskSpec:
    return TaskSpec(
        task_id=task_id, job_id=b"j" * 4, task_type=NORMAL_TASK,
        function_id=b"f" * 16, function_name="t", args=[], kwargs={},
        num_returns=1, resources={}, owner_addr={}, function_blob=blob,
        max_retries=max_retries)


def _retained(ledger, w, task_id, blob=b"", live=(b"o1",), completed=True):
    record = TaskRecord(_spec(task_id, blob=blob), [])
    record.completed = completed
    w._tasks[task_id] = record
    assert ledger.retain(record, list(live))
    return record


def test_ledger_refcount_keep_drop():
    """A record stays while ANY live output anchors it; the last output's
    death drops it (caller unpins); unknown tasks are untracked."""
    w = _FakeWorker()
    ledger = LineageLedger(w)
    _retained(ledger, w, b"t1" * 8, blob=b"x" * 100, live=(b"a", b"b"))
    assert ledger.is_retained(b"t1" * 8)
    assert ledger.bytes == 512 + 100
    assert ledger.on_output_freed(b"t1" * 8, b"a") == "keep"
    assert ledger.is_retained(b"t1" * 8)
    assert ledger.on_output_freed(b"t1" * 8, b"b") == "drop"
    assert not ledger.is_retained(b"t1" * 8)
    assert ledger.bytes == 0
    assert ledger.on_output_freed(b"t1" * 8, b"b") == "untracked"
    assert ledger.on_output_freed(b"??" * 8, b"c") == "untracked"


def test_ledger_retain_idempotent_keeps_first_live_set():
    """A replay's second completion must NOT resurrect outputs freed
    while the replay ran."""
    w = _FakeWorker()
    ledger = LineageLedger(w)
    record = _retained(ledger, w, b"t2" * 8, live=(b"a", b"b"))
    assert ledger.on_output_freed(b"t2" * 8, b"a") == "keep"
    # second retain (same record, replay finished) is a no-op
    assert ledger.retain(record, [b"a", b"b"])
    assert ledger.on_output_freed(b"t2" * 8, b"b") == "drop"
    assert ledger.bytes == 0


def test_ledger_evict_on_cap_fifo(monkeypatch):
    """Crossing lineage_max_bytes evicts the OLDEST completed record:
    entry gone, bytes/evictions accounted, task popped and args unpinned."""
    monkeypatch.setenv("RAY_TPU_LINEAGE_MAX_BYTES", "2000")
    w = _FakeWorker()
    ledger = LineageLedger(w)
    blob = b"x" * 1000  # each record estimates 512 + 1000 = 1512
    _retained(ledger, w, b"t1" * 8, blob=blob)
    assert ledger.evictions == 0
    _retained(ledger, w, b"t2" * 8, blob=blob)  # 3024 > 2000: evict t1
    assert ledger.evictions == 1
    assert not ledger.is_retained(b"t1" * 8)
    assert ledger.is_retained(b"t2" * 8)
    assert ledger.bytes == 1512
    assert b"t1" * 8 not in w._tasks
    assert w.unpinned == [b"t1" * 8]
    assert ledger.summary()["records"] == 1


def test_ledger_cap_skips_inflight_replay(monkeypatch):
    """A record whose replay is in flight (completed=False) is not
    evictable: it rotates to the back and the next victim is taken."""
    monkeypatch.setenv("RAY_TPU_LINEAGE_MAX_BYTES", "2000")
    w = _FakeWorker()
    ledger = LineageLedger(w)
    blob = b"x" * 1000
    _retained(ledger, w, b"t1" * 8, blob=blob, completed=False)
    _retained(ledger, w, b"t2" * 8, blob=blob)
    # t1 is mid-replay: protected. t2 (completed) pays the cap instead.
    assert ledger.is_retained(b"t1" * 8)
    assert not ledger.is_retained(b"t2" * 8)
    assert ledger.evictions == 1
    assert w.unpinned == [b"t2" * 8]


def test_ledger_replay_listener_weak():
    """notify_replay fans out to subscribers; a bound-method listener is
    weakly held, so the subscriber dying IS the unsubscribe (how a
    finished shuffle exchange stops hearing about replays)."""
    ledger = LineageLedger(_FakeWorker())

    class Sub:
        def __init__(self):
            self.heard = []

        def on_replay(self, task_binary):
            self.heard.append(task_binary)

    sub = Sub()
    ledger.add_listener(sub.on_replay)
    seen = []
    ledger.add_listener(lambda tb: seen.append(tb))  # plain callable: strong

    def boom(_tb):
        raise RuntimeError("listener errors must not break recovery")

    ledger.add_listener(boom)
    ledger.notify_replay(b"t1" * 8)
    assert sub.heard == [b"t1" * 8]
    assert seen == [b"t1" * 8]

    del sub
    ledger.notify_replay(b"t2" * 8)  # dead WeakMethod pruned, no error
    assert seen == [b"t1" * 8, b"t2" * 8]
    assert len(ledger._listeners) == 2  # lambda + boom survive


def test_replay_seed_deterministic():
    """The seed is a pure function of the task id (rides every
    resubmission of the spec), differs across tasks, and fits the
    non-negative 63-bit range random.seed/np.random.seed accept."""
    a = _replay_seed(b"t1" * 8)
    assert a == _replay_seed(b"t1" * 8)
    assert a != _replay_seed(b"t2" * 8)
    assert 0 <= a < 2 ** 63
    # the executor-side seeding produces identical stdlib draws
    from ray_tpu._private.worker_process import _seed_task_rng
    import random

    state = random.getstate()
    try:
        _seed_task_rng(a)
        first = [random.random() for _ in range(8)]
        _seed_task_rng(a)
        assert [random.random() for _ in range(8)] == first
    finally:
        random.setstate(state)


# ---------------------------------------------------------------------------
# cluster tests: one module-scoped head, per-test side nodes
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def lineage_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    ray_tpu.init(_node=cluster.head_node)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _kill_and_replace(cluster, node, res_key):
    """Kill the side node holding the only copies, then give replays a
    fresh feasible node (idiom from test_object_recovery)."""
    cluster.remove_node(node)
    replacement = cluster.add_node(num_cpus=2, resources={res_key: 2})
    cluster.wait_for_nodes()
    time.sleep(2.5)  # node-death detection lag (~2s health check)
    return replacement


def test_chain_reconstruction_argument_also_lost(lineage_cluster):
    """Depth-2 chain: the lost object's producing task has an ARGUMENT
    whose only copy died on the same node — the owner replays the
    argument's task first, then the consumer, all under original ids."""
    cluster = lineage_cluster
    node = cluster.add_node(num_cpus=2, resources={"lin_chain": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=2, resources={"lin_chain": 1})
    def base():
        return np.full(200_000, 3, np.int64)

    @ray_tpu.remote(max_retries=2, resources={"lin_chain": 1})
    def derive(x):
        return x * 2 + 1

    a = base.remote()
    b = derive.remote(a)
    ready, _ = ray_tpu.wait([b], num_returns=1, timeout=120)
    assert ready, "chain did not finish"

    w = worker_mod.global_worker
    before = w._lineage.reconstructions
    _kill_and_replace(cluster, node, "lin_chain")

    value = ray_tpu.get(b, timeout=180)
    assert value.shape == (200_000,)
    assert int(value[0]) == 7
    # both hops replayed: base (the lost argument) and derive
    assert w._lineage.reconstructions >= before + 2
    del a, b


def test_replay_byte_identity_with_rng(lineage_cluster):
    """A task body drawing stdlib randomness reconstructs BYTE-IDENTICAL:
    the replay_seed stamped on the spec rides the resubmission."""
    cluster = lineage_cluster
    node = cluster.add_node(num_cpus=2, resources={"lin_rng": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=2, resources={"lin_rng": 1})
    def produce_random():
        import random

        arr = np.zeros(200_000)
        arr[:64] = [random.random() for _ in range(64)]
        return arr

    @ray_tpu.remote(max_retries=2, resources={"lin_rng": 1})
    def sha(x):
        return hashlib.sha256(x.tobytes()).hexdigest()

    ref = produce_random.remote()
    # hash on the SAME node: a driver get() would pull a head-side
    # replica and the kill below would lose nothing
    h1 = ray_tpu.get(sha.remote(ref), timeout=120)

    _kill_and_replace(cluster, node, "lin_rng")

    second = ray_tpu.get(ref, timeout=180)
    assert len(set(second[:64])) > 32  # the draws actually happened
    assert hashlib.sha256(second.tobytes()).hexdigest() == h1
    del ref


def test_depth_and_attempt_bounds_raise_typed_error(lineage_cluster,
                                                    monkeypatch):
    """Exhausted bounds surface ObjectReconstructionFailedError carrying
    the attempted chain — never a silent hang or a bare timeout."""
    cluster = lineage_cluster
    node = cluster.add_node(num_cpus=2, resources={"lin_bound": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=2, resources={"lin_bound": 1})
    def produce():
        return np.full(150_000, 5, np.int64)

    r1 = produce.remote()
    r2 = produce.remote()
    ready, _ = ray_tpu.wait([r1, r2], num_returns=2, timeout=120)
    assert len(ready) == 2
    _kill_and_replace(cluster, node, "lin_bound")

    w = worker_mod.global_worker
    monkeypatch.setenv("RAY_TPU_LINEAGE_MAX_RECONSTRUCTION_DEPTH", "0")
    with pytest.raises(ObjectReconstructionFailedError) as ei:
        w._try_recover(r1, 1)
    assert "depth" in str(ei.value)
    assert ei.value.chain and ei.value.chain[-1]["why"] == "depth cap"
    monkeypatch.delenv("RAY_TPU_LINEAGE_MAX_RECONSTRUCTION_DEPTH")

    monkeypatch.setenv("RAY_TPU_LINEAGE_MAX_RECONSTRUCTION_ATTEMPTS", "0")
    with pytest.raises(ObjectReconstructionFailedError) as ei:
        w._try_recover(r2, 1)
    assert "attempts" in str(ei.value)
    monkeypatch.delenv("RAY_TPU_LINEAGE_MAX_RECONSTRUCTION_ATTEMPTS")
    # bounds restored: the normal path still rebuilds both
    assert int(ray_tpu.get(r1, timeout=180)[0]) == 5
    assert int(ray_tpu.get(r2, timeout=180)[0]) == 5
    del r1, r2


def test_put_has_no_task_lineage(lineage_cluster):
    """put() objects carry no producing task: reconstruction must refuse
    with the typed error (why names put()), not retry forever."""
    ref = ray_tpu.put(np.full(150_000, 9, np.int64))
    w = worker_mod.global_worker
    with pytest.raises(ObjectReconstructionFailedError) as ei:
        w._try_recover(ref, 1)
    assert "put()" in str(ei.value)
    assert ei.value.chain and "put()" in ei.value.chain[-1]["why"]
    del ref


def test_daemonkiller_agent_sigkill_chaos(lineage_cluster, monkeypatch):
    """Chaos flavor of node loss: SIGKILL the side node's agent daemon
    (DaemonKiller, not a graceful remove) mid-hold; every ref rebuilds."""
    from ray_tpu.util.chaos import DaemonKiller

    monkeypatch.setenv("RAY_TPU_PULL_DEAD_HOLDER_ROUNDS", "3")
    monkeypatch.setenv("RAY_TPU_OBJECT_PULL_DEADLINE_S", "90")
    cluster = lineage_cluster
    node = cluster.add_node(num_cpus=2, resources={"lin_chaos": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=2, resources={"lin_chaos": 1})
    def produce(i):
        return np.full(150_000, i, np.int64)

    refs = [produce.remote(i) for i in range(4)]
    ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
    assert len(ready) == len(refs)

    killer = DaemonKiller(cluster.session_dir, roles=("agent",), max_kills=1)
    record = killer.kill_target(
        {"role": "agent", "pid": node.agent_proc.pid})
    assert record is not None, "victim agent was not killed"
    # the killed node is still registered until the health check lapses;
    # bring up the replacement and let death detection settle
    cluster.worker_nodes.remove(node)
    cluster.add_node(num_cpus=2, resources={"lin_chaos": 2})
    time.sleep(4.0)

    w = worker_mod.global_worker
    before = w._lineage.reconstructions
    for i, ref in enumerate(refs):
        assert int(ray_tpu.get(ref, timeout=180)[0]) == i
    assert w._lineage.reconstructions > before
    del refs
