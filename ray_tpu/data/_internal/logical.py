"""Logical plan for ray_tpu.data.

A ``Dataset`` is an immutable chain of ``LogicalOperator`` nodes (reference:
python/ray/data/_internal/logical/operators/). The optimizer rewrites the
chain (fusion, limit pushdown — reference: _internal/logical/rules/) before
the planner lowers it to physical operators.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


class LogicalOperator:
    name: str = "op"

    def __init__(self, input_op: Optional["LogicalOperator"] = None):
        self.input_op = input_op

    def chain(self) -> List["LogicalOperator"]:
        ops: List[LogicalOperator] = []
        op: Optional[LogicalOperator] = self
        while op is not None:
            ops.append(op)
            op = op.input_op
        return list(reversed(ops))

    def __repr__(self):
        return self.name


class Read(LogicalOperator):
    """Source: a list of read tasks, each producing one or more blocks
    (reference: logical/operators/read_operator.py)."""

    def __init__(self, read_tasks: List[Callable[[], Any]], name: str = "Read"):
        super().__init__(None)
        self.read_tasks = read_tasks
        self.name = name


class InputData(LogicalOperator):
    """Source: pre-materialized (block_ref, metadata) bundles."""

    name = "FromBlocks"

    def __init__(self, bundles: List[Tuple[Any, Any]]):
        super().__init__(None)
        self.bundles = bundles


@dataclasses.dataclass
class MapSpec:
    """One fused-able row/batch transform stage."""

    kind: str  # "batches" | "rows" | "filter" | "flat"
    fn: Any  # callable, or class for actor compute
    fn_args: tuple = ()
    fn_kwargs: Optional[dict] = None
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: Optional[dict] = None
    batch_size: Optional[int] = None
    batch_format: str = "numpy"


class AbstractMap(LogicalOperator):
    """Any 1-in/1-out transform executed as parallel tasks or an actor pool
    (reference: logical/operators/map_operator.py)."""

    def __init__(self, input_op: LogicalOperator, spec: MapSpec, name: str,
                 compute: Optional[Any] = None,
                 ray_remote_args: Optional[Dict] = None):
        super().__init__(input_op)
        self.specs = [spec]
        self.name = name
        self.compute = compute
        self.ray_remote_args = ray_remote_args or {}


class Limit(LogicalOperator):
    def __init__(self, input_op: LogicalOperator, limit: int):
        super().__init__(input_op)
        self.limit = limit
        self.name = f"Limit[{limit}]"


class AbstractAllToAll(LogicalOperator):
    """Barrier ops: repartition / shuffle / sort / aggregate
    (reference: logical/operators/all_to_all_operator.py)."""

    def __init__(self, input_op: LogicalOperator, kind: str, name: str,
                 **kwargs):
        super().__init__(input_op)
        self.kind = kind
        self.name = name
        self.kwargs = kwargs


class Union(LogicalOperator):
    def __init__(self, input_op: LogicalOperator,
                 others: List[LogicalOperator]):
        super().__init__(input_op)
        self.others = others
        self.name = "Union"


class Zip(LogicalOperator):
    def __init__(self, input_op: LogicalOperator, other: LogicalOperator):
        super().__init__(input_op)
        self.other = other
        self.name = "Zip"


class Write(LogicalOperator):
    def __init__(self, input_op: LogicalOperator, write_fn: Callable,
                 name: str = "Write"):
        super().__init__(input_op)
        self.write_fn = write_fn
        self.name = name
