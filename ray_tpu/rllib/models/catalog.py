"""Model catalog — CNN and RNN modules beyond the default MLP (reference:
rllib/models/catalog.py + rllib/models/torch/{visionnet,recurrent_net}.py;
VERDICT r1 item 4: a minimal catalog so algorithms run beyond MLP envs).

All modules keep the functional RLModule contract (params are a pytree,
``forward(params, obs)`` is pure), so the same module runs jitted in the
Learner and on CPU env runners.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.rllib.core.rl_module import Categorical, DiagGaussian

# (out_channels, kernel, stride) — the reference's default vision net for
# 84x84-ish inputs; smaller images get shallower stacks
DEFAULT_CONV_FILTERS = ((16, 4, 2), (32, 4, 2), (64, 3, 2))


def default_filters_for(obs_shape) -> tuple:
    side = min(obs_shape[0], obs_shape[1])
    if side >= 36:
        return DEFAULT_CONV_FILTERS
    if side >= 10:
        return ((16, 4, 2), (32, 3, 2))
    return ((16, 3, 1),)


def _mlp_params(key, sizes, final_scale: float = 0.01):
    layers = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / a)
        if i == len(sizes) - 2:
            scale = scale * final_scale
        layers.append({"w": jax.random.normal(sub, (a, b)) * scale,
                       "b": jnp.zeros((b,))})
    return layers


def _mlp_forward(layers, x, act):
    for layer in layers[:-1]:
        x = act(x @ layer["w"] + layer["b"])
    last = layers[-1]
    return x @ last["w"] + last["b"]


class ConvModule:
    """Vision policy/value net: shared conv torso, separate heads
    (reference: rllib/models/torch/visionnet.py)."""

    def __init__(self, spec):
        self.spec = spec
        self.dist = Categorical if spec.discrete else DiagGaussian
        self._act = jax.nn.relu
        self._obs_shape = tuple(spec.obs_shape)  # (H, W, C)
        self._filters = tuple(getattr(spec, "conv_filters", None)
                              or default_filters_for(self._obs_shape))
        self._out_dim = (spec.action_dim if spec.discrete
                         else 2 * spec.action_dim)
        if self._torso_out_dim() <= 0:
            raise ValueError(
                f"conv_filters {self._filters} collapse obs_shape "
                f"{self._obs_shape} to zero spatial extent; pass smaller "
                "kernels/strides via RLModuleSpec.conv_filters")

    def init(self, rng) -> Dict:
        params: Dict = {"conv": []}
        in_c = self._obs_shape[-1]
        for out_c, k, _s in self._filters:
            rng, sub = jax.random.split(rng)
            fan_in = k * k * in_c
            params["conv"].append({
                "w": jax.random.normal(sub, (k, k, in_c, out_c))
                * jnp.sqrt(2.0 / fan_in),
                "b": jnp.zeros((out_c,)),
            })
            in_c = out_c
        flat = self._torso_out_dim()
        k1, k2 = jax.random.split(jax.random.fold_in(rng, 7))
        params["pi"] = _mlp_params(k1, (flat, 256, self._out_dim))
        params["vf"] = _mlp_params(k2, (flat, 256, 1), final_scale=1.0)
        return params

    def _torso_out_dim(self) -> int:
        h, w, _ = self._obs_shape
        for _c, k, s in self._filters:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
        return h * w * self._filters[-1][0]

    def _torso(self, params, obs):
        x = obs
        if x.ndim == len(self._obs_shape):  # add batch dim
            x = x[None]
        for layer, (_c, _k, stride) in zip(params["conv"], self._filters):
            x = jax.lax.conv_general_dilated(
                x, layer["w"], window_strides=(stride, stride),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = self._act(x + layer["b"])
        return x.reshape(x.shape[0], -1)

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        squeeze = obs.ndim == len(self._obs_shape)
        feats = self._torso(params, obs)
        logits = _mlp_forward(params["pi"], feats, self._act)
        vf = _mlp_forward(params["vf"], feats, self._act)[..., 0]
        if squeeze:
            logits, vf = logits[0], vf[0]
        return {"logits": logits, "vf": vf}

    def explore_action(self, params, obs, rng):
        out = self.forward(params, obs)
        action = self.dist.sample(rng, out["logits"])
        logp = self.dist.logp(out["logits"], action)
        return action, logp, out["vf"]


class LSTMModule:
    """Recurrent policy/value net: MLP encoder -> LSTM cell -> heads
    (reference: rllib/models/torch/recurrent_net.py LSTMWrapper).

    ``forward_recurrent(params, obs_seq, state)`` scans a [T, B, obs]
    sequence carrying (h, c); ``initial_state(batch)`` builds zeros.
    ``forward(params, obs)`` is the stateless facade env runners use —
    zero state per call — so the module stays drop-in where recurrence
    isn't plumbed.
    """

    def __init__(self, spec):
        self.spec = spec
        self.dist = Categorical if spec.discrete else DiagGaussian
        self._act = jnp.tanh
        self.cell_size = int(getattr(spec, "lstm_cell_size", 64) or 64)
        self._out_dim = (spec.action_dim if spec.discrete
                         else 2 * spec.action_dim)

    def init(self, rng) -> Dict:
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        enc_sizes = (self.spec.obs_dim, *self.spec.hiddens)
        H, E = self.cell_size, enc_sizes[-1]
        scale = jnp.sqrt(1.0 / (E + H))
        return {
            "enc": _mlp_params(k1, enc_sizes, final_scale=1.0),
            "lstm": {
                "wx": jax.random.normal(k2, (E, 4 * H)) * scale,
                "wh": jax.random.normal(k3, (H, 4 * H)) * scale,
                "b": jnp.zeros((4 * H,)),
            },
            "pi": _mlp_params(jax.random.fold_in(k4, 0),
                              (H, self._out_dim)),
            "vf": _mlp_params(jax.random.fold_in(k4, 1), (H, 1),
                              final_scale=1.0),
        }

    def initial_state(self, batch_size: int) -> Tuple:
        return (jnp.zeros((batch_size, self.cell_size)),
                jnp.zeros((batch_size, self.cell_size)))

    def _encode(self, params, obs):
        x = obs
        for layer in params["enc"]:
            x = self._act(x @ layer["w"] + layer["b"])
        return x

    def _cell(self, params, x, state):
        h, c = state
        gates = x @ params["lstm"]["wx"] + h @ params["lstm"]["wh"] \
            + params["lstm"]["b"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, (h, c)

    def _heads(self, params, h):
        logits = _mlp_forward(params["pi"], h, self._act)
        vf = _mlp_forward(params["vf"], h, self._act)[..., 0]
        return {"logits": logits, "vf": vf}

    def forward_recurrent(self, params, obs_seq, state):
        """obs_seq: [T, B, obs_dim]; returns ({logits, vf}: [T, B, ...],
        final_state)."""
        enc = self._encode(params, obs_seq)

        def step(carry, x):
            h, new_carry = self._cell(params, x, carry)
            return new_carry, h

        final_state, hs = jax.lax.scan(step, state, enc)
        return self._heads(params, hs), final_state

    def forward(self, params, obs) -> Dict[str, jnp.ndarray]:
        squeeze = obs.ndim == 1
        x = obs[None] if squeeze else obs
        enc = self._encode(params, x)
        h, _ = self._cell(params, enc, self.initial_state(x.shape[0]))
        out = self._heads(params, h)
        if squeeze:
            out = {k: v[0] for k, v in out.items()}
        return out

    def explore_action(self, params, obs, rng):
        out = self.forward(params, obs)
        action = self.dist.sample(rng, out["logits"])
        logp = self.dist.logp(out["logits"], action)
        return action, logp, out["vf"]


def get_module_for_space(spec):
    """Catalog dispatch (reference: catalog.py get_model_v2): image obs ->
    ConvModule, use_lstm -> LSTMModule, else the default MLP."""
    from ray_tpu.rllib.core.rl_module import MLPModule

    is_image = bool(getattr(spec, "conv_filters", None)) or \
        len(getattr(spec, "obs_shape", ()) or ()) == 3
    use_lstm = bool(getattr(spec, "use_lstm", False))
    if is_image and use_lstm:
        raise ValueError(
            "conv+lstm composition is not supported yet; pick "
            "conv_filters/obs_shape OR use_lstm")
    if is_image:
        if getattr(spec, "obs_shape", None) is None or \
                len(spec.obs_shape) != 3:
            raise ValueError(
                "conv_filters requires obs_shape=(H, W, C) on the spec")
        return ConvModule(spec)
    if use_lstm:
        return LSTMModule(spec)
    return MLPModule(spec)
