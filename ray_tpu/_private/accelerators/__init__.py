from ray_tpu._private.accelerators.accelerator import AcceleratorManager
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager
from ray_tpu._private.accelerators.nvidia_gpu import NvidiaGPUAcceleratorManager
from ray_tpu._private.accelerators.other import (
    AMDGPUAcceleratorManager,
    HPUAcceleratorManager,
    IntelGPUAcceleratorManager,
    NeuronAcceleratorManager,
    NPUAcceleratorManager,
)


class _GPUChain:
    """One node runs one GPU family (reference assumption): Nvidia is
    probed first, then AMD (kfd), then Intel (DRM) — the first family
    reporting devices owns the node's GPU resource + visibility env."""

    CHAIN = (NvidiaGPUAcceleratorManager, AMDGPUAcceleratorManager,
             IntelGPUAcceleratorManager)

    @classmethod
    def _active(cls):
        for manager in cls.CHAIN:
            try:
                if manager.get_current_node_num_accelerators():
                    return manager
            except Exception:
                continue
        return cls.CHAIN[0]

    @classmethod
    def get_resource_name(cls):
        return "GPU"

    @classmethod
    def get_current_node_num_accelerators(cls):
        return cls._active().get_current_node_num_accelerators()

    @classmethod
    def get_current_node_additional_resources(cls):
        return cls._active().get_current_node_additional_resources()

    @classmethod
    def get_visible_accelerator_ids_env_var(cls):
        return cls._active().get_visible_accelerator_ids_env_var()

    @classmethod
    def set_visible_accelerator_ids(cls, ids):
        return cls._active().set_visible_accelerator_ids(ids)


def get_all_accelerator_managers():
    return {
        "TPU": TPUAcceleratorManager,
        "GPU": _GPUChain,
        "neuron_cores": NeuronAcceleratorManager,
        "HPU": HPUAcceleratorManager,
        "NPU": NPUAcceleratorManager,
    }


def get_accelerator_manager(resource_name: str):
    return get_all_accelerator_managers().get(resource_name)
