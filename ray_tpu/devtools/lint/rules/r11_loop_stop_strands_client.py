"""R11 — event-loop stop in a class holding an ``AsyncRpcClient``
without awaiting the client's read loop first.

Invariant: a class that owns a private event-loop thread AND an
``AsyncRpcClient`` must route teardown through ``aclose()`` /
``close_soon()`` *before* stopping the loop. ``client.close()`` only
*cancels* the read-loop task; the cancelled task still needs one loop
tick to finish, so a method that stops the loop without awaiting it
strands the task and the dying loop prints "Task was destroyed but it
is pending!" at interpreter teardown.

Motivating bug: the BENCH tail-leak (ISSUE 17 satellite) —
``util/client/client.py::_Channel.close`` and
``autoscaler/monitor.py::GcsChannel.close`` both did
``self._loop.call_soon_threadsafe(self._loop.stop)`` with the client's
cancelled read loop still pending, spamming the bench tail whenever a
client-mode driver or the autoscaler monitor shut down.

Detection: inside a class whose body constructs an ``AsyncRpcClient``,
a method that stops an event loop (``<loop>.stop()`` directly, or
``call_soon_threadsafe(<loop>.stop)``) while the method body never
references ``aclose`` or ``close_soon``.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import _call_name
from ..model import ModuleInfo, Violation

RULE_ID = "R11"
SUMMARY = ("loop stopped in a class holding an AsyncRpcClient without "
           "aclose()/close_soon() — the cancelled read-loop task is "
           "stranded and the dying loop warns 'Task was destroyed but "
           "it is pending!'; await the client's aclose() on the loop "
           "before stopping it")


def _is_loop_stop(node: ast.AST) -> bool:
    """``<x>.stop()`` where x looks like a loop, or
    ``<x>.call_soon_threadsafe(<y>.stop, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    base, attr = _call_name(node.func)
    if attr == "call_soon_threadsafe":
        return any(isinstance(a, ast.Attribute) and a.attr == "stop"
                   for a in node.args)
    if attr == "stop" and isinstance(node.func, ast.Attribute):
        # direct <loop>.stop(): only when the receiver names a loop, so
        # Monitor.stop() / watchdog.stop() style APIs don't trip
        v = node.func.value
        name = (v.attr if isinstance(v, ast.Attribute)
                else v.id if isinstance(v, ast.Name) else "")
        return "loop" in name.lower()
    return False


def _holds_async_client(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            base, attr = _call_name(node.func)
            if attr == "AsyncRpcClient":
                return True
    return False


def check_module(mod: ModuleInfo, index) -> List[Violation]:
    out: List[Violation] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef) or not _holds_async_client(cls):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stops = [n for n in ast.walk(fn) if _is_loop_stop(n)]
            if not stops:
                continue
            mentioned = {n.attr for n in ast.walk(fn)
                         if isinstance(n, ast.Attribute)}
            mentioned |= {n.id for n in ast.walk(fn)
                          if isinstance(n, ast.Name)}
            if "aclose" in mentioned or "close_soon" in mentioned:
                continue
            out.append(mod.violation(
                RULE_ID, stops[0],
                f"'{mod.qualname(fn)}' stops the event loop while this "
                f"class holds an AsyncRpcClient and the method never "
                f"awaits aclose()/close_soon(): the client's cancelled "
                f"read-loop task needs one more loop tick, so stopping "
                f"first strands it ('Task was destroyed but it is "
                f"pending!' at teardown) — run "
                f"run_coroutine_threadsafe(client.aclose(), loop)"
                f".result() before stopping the loop"))
    return out
