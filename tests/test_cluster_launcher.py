"""Cluster launcher tests (VERDICT r2 missing #2 tail; reference:
autoscaler/_private/commands.py + command_runner.py). Command
construction and orchestration order are tested with a recording fake
runner; the end-to-end `up` runs with the LOCAL runner — a real
two-node cluster launched through the actual CLI path, the reference's
fake-multinode discipline."""

import json

import pytest

from ray_tpu.autoscaler.launcher import (
    ClusterLauncher, LocalCommandRunner, SSHCommandRunner,
    load_cluster_config, validate_cluster_config)


class RecordingRunner:
    log = []

    def __init__(self, host):
        self.host = host

    def run(self, cmd, timeout=300.0):
        RecordingRunner.log.append((self.host, cmd))
        if "cli start --head" in cmd.replace("'", ""):
            return 0, "node started\nhead address: 127.0.0.1:7399\n"
        return 0, "ok"

    check = SSHCommandRunner.check


@pytest.fixture(autouse=True)
def _clear_log():
    RecordingRunner.log = []


CONFIG = {
    "cluster_name": "t",
    "provider": {"type": "ssh", "ssh_user": "u", "ssh_private_key": "/k"},
    "head_node": {"host": "10.0.0.1", "port": 7399,
                  "resources": {"CPU": 4}},
    "worker_nodes": [
        {"host": "10.0.0.2", "resources": {"CPU": 4, "TPU": 4}},
        {"host": "10.0.0.3"},
    ],
    "setup_commands": ["echo ready"],
}


class TestValidation:
    def test_head_required(self):
        with pytest.raises(ValueError, match="head_node"):
            validate_cluster_config({"worker_nodes": []})

    def test_provider_type(self):
        with pytest.raises(ValueError, match="provider.type"):
            validate_cluster_config(
                {"head_node": {"host": "h"},
                 "provider": {"type": "k8s"}})

    def test_yaml_and_json_load(self, tmp_path):
        y = tmp_path / "c.yaml"
        y.write_text("head_node:\n  host: h1\n")
        assert load_cluster_config(str(y))["head_node"]["host"] == "h1"
        j = tmp_path / "c.json"
        j.write_text(json.dumps(CONFIG))
        assert load_cluster_config(str(j))["cluster_name"] == "t"


class TestOrchestration:
    def test_up_order_and_commands(self):
        launcher = ClusterLauncher(CONFIG, runner_factory=RecordingRunner,
                                   python="python")
        address = launcher.up()
        # the head reports loopback; workers must dial the routable host
        assert address == "10.0.0.1:7399"
        hosts = [h for h, _ in RecordingRunner.log]
        # setup+start on head first, then each worker
        assert hosts == ["10.0.0.1", "10.0.0.1",
                         "10.0.0.2", "10.0.0.2", "10.0.0.3", "10.0.0.3"]
        head_start = RecordingRunner.log[1][1]
        assert "--head" in head_start and "--port 7399" in head_start
        w1 = RecordingRunner.log[3][1]
        assert "--address '10.0.0.1:7399'" in w1 or \
            "--address 10.0.0.1:7399" in w1
        assert "TPU" in w1  # resources forwarded
        w2 = RecordingRunner.log[5][1]
        assert "--resources" not in w2

    def test_down_stops_workers_then_head(self):
        launcher = ClusterLauncher(CONFIG, runner_factory=RecordingRunner)
        launcher.down()
        hosts = [h for h, _ in RecordingRunner.log]
        assert hosts == ["10.0.0.2", "10.0.0.3", "10.0.0.1"]
        assert all("stop" in c for _, c in RecordingRunner.log)

    def test_ssh_command_shape(self):
        r = SSHCommandRunner("10.0.0.9", user="u", private_key="/k",
                             ssh_options=["-p", "2222"])
        base = r._base()
        assert base[0] == "ssh" and "BatchMode=yes" in " ".join(base)
        assert "-i" in base and "/k" in base
        assert base[-1] == "u@10.0.0.9"
        assert "2222" in base


class TestEndToEndLocal:
    def test_up_and_down_local(self, tmp_path):
        """Real `up`: head + one worker launched through the actual CLI
        on this machine, verified by connecting a driver."""
        import ray_tpu

        config = {
            "cluster_name": "local-e2e",
            "provider": {"type": "local"},
            "head_node": {"host": "127.0.0.1",
                          "resources": {"CPU": 2, "head_marker": 1}},
            "worker_nodes": [{"host": "127.0.0.1",
                              "resources": {"CPU": 2, "worker_marker": 1}}],
        }
        launcher = ClusterLauncher(config)
        address = launcher.up()
        try:
            ray_tpu.init(address=address)
            import time

            deadline = time.time() + 60
            while time.time() < deadline:
                total = ray_tpu.cluster_resources()
                if total.get("head_marker") and total.get("worker_marker"):
                    break
                time.sleep(1)
            assert total.get("head_marker") == 1.0, total
            assert total.get("worker_marker") == 1.0, total

            @ray_tpu.remote(resources={"worker_marker": 1})
            def on_worker():
                return "hi"

            assert ray_tpu.get(on_worker.remote(), timeout=120) == "hi"
        finally:
            ray_tpu.shutdown()
            launcher.down()
