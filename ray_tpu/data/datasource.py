"""Datasources: each produces a list of read tasks (closures returning
blocks), one per file/fragment, so reads parallelize as tasks
(reference: python/ray/data/datasource/ + read_api.py).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix}" if suffix else "*")
            out.extend(sorted(f for f in glob.glob(pat, recursive=True)
                              if os.path.isfile(f)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class Datasource:
    """ABC (reference: datasource/datasource.py Datasource/Reader)."""

    name = "Datasource"

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    name = "Range"

    def __init__(self, n: int, tensor_shape: Optional[tuple] = None,
                 column: str = "id"):
        self.n = n
        self.tensor_shape = tensor_shape
        self.column = column

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        parallelism = max(1, min(parallelism, self.n or 1))
        tasks = []
        per = (self.n + parallelism - 1) // parallelism if self.n else 0
        for i in range(parallelism):
            lo, hi = i * per, min((i + 1) * per, self.n)
            if lo >= hi and self.n > 0:
                continue
            shape, col = self.tensor_shape, self.column

            def read(lo=lo, hi=hi):
                ids = np.arange(lo, hi, dtype=np.int64)
                if shape is None:
                    return {col: ids}
                data = np.broadcast_to(
                    ids.reshape((-1,) + (1,) * len(shape)),
                    (hi - lo,) + shape).astype(np.float64)
                return {col: np.ascontiguousarray(data)}

            tasks.append(read)
        return tasks or [lambda: {self.column: np.asarray([], np.int64)}]


class ItemsDatasource(Datasource):
    name = "FromItems"

    def __init__(self, items: List[Any]):
        self.items = items

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        from ray_tpu.data.block import BlockAccessor

        n = len(self.items)
        parallelism = max(1, min(parallelism, n or 1))
        per = (n + parallelism - 1) // parallelism if n else 0
        tasks = []
        for i in range(parallelism):
            chunk = self.items[i * per:(i + 1) * per]
            if not chunk and n > 0:
                continue
            tasks.append(lambda chunk=chunk: BlockAccessor.rows_to_block(chunk))
        return tasks or [lambda: BlockAccessor.rows_to_block([])]


class FileDatasource(Datasource):
    """One read task per file."""

    suffix: Optional[str] = None

    def __init__(self, paths, **read_kwargs):
        self.paths = _expand_paths(paths, self.suffix)
        self.read_kwargs = read_kwargs

    def read_file(self, path: str) -> Any:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        return [lambda p=p: self.read_file(p) for p in self.paths]


class ParquetDatasource(FileDatasource):
    name = "ReadParquet"
    suffix = ".parquet"

    def read_file(self, path: str):
        import pyarrow.parquet as pq

        return pq.read_table(path, **self.read_kwargs)


class CSVDatasource(FileDatasource):
    name = "ReadCSV"
    suffix = ".csv"

    def read_file(self, path: str):
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path, **self.read_kwargs)


class JSONDatasource(FileDatasource):
    name = "ReadJSON"
    suffix = ".json"

    def read_file(self, path: str):
        import pyarrow.json as pajson

        return pajson.read_json(path, **self.read_kwargs)


class TextDatasource(FileDatasource):
    name = "ReadText"
    suffix = None

    def read_file(self, path: str):
        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        return {"text": np.asarray(lines, dtype=object)}


class BinaryDatasource(FileDatasource):
    name = "ReadBinary"
    suffix = None

    def read_file(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        return {"bytes": np.asarray([data], dtype=object),
                "path": np.asarray([path], dtype=object)}


class NumpyDatasource(FileDatasource):
    name = "ReadNumpy"
    suffix = ".npy"

    def read_file(self, path: str):
        return {"data": np.load(path)}


# ------------------------------------------------------------------ writers
def write_parquet_fn(path: str):
    os.makedirs(path, exist_ok=True)

    def write(batch):
        import uuid

        import pyarrow.parquet as pq

        from ray_tpu.data.block import BlockAccessor

        table = BlockAccessor(BlockAccessor.batch_to_block(batch)).to_arrow()
        fn = os.path.join(path, f"part-{uuid.uuid4().hex[:12]}.parquet")
        pq.write_table(table, fn)
        return {"path": np.asarray([fn], dtype=object),
                "num_rows": np.asarray([table.num_rows])}

    return write


def write_csv_fn(path: str):
    os.makedirs(path, exist_ok=True)

    def write(batch):
        import uuid

        import pyarrow.csv as pacsv

        from ray_tpu.data.block import BlockAccessor

        table = BlockAccessor(BlockAccessor.batch_to_block(batch)).to_arrow()
        fn = os.path.join(path, f"part-{uuid.uuid4().hex[:12]}.csv")
        pacsv.write_csv(table, fn)
        return {"path": np.asarray([fn], dtype=object),
                "num_rows": np.asarray([table.num_rows])}

    return write


def write_json_fn(path: str):
    os.makedirs(path, exist_ok=True)

    def write(batch):
        import json
        import uuid

        from ray_tpu.data.block import BlockAccessor

        acc = BlockAccessor(BlockAccessor.batch_to_block(batch))
        fn = os.path.join(path, f"part-{uuid.uuid4().hex[:12]}.json")
        with open(fn, "w") as f:
            for row in acc.iter_rows():
                f.write(json.dumps(
                    {k: (v.tolist() if isinstance(v, np.ndarray)
                         else v.item() if isinstance(v, np.generic) else v)
                     for k, v in row.items()}) + "\n")
        return {"path": np.asarray([fn], dtype=object),
                "num_rows": np.asarray([acc.num_rows()])}

    return write
