"""joblib ParallelBackend over cluster tasks (reference:
python/ray/util/joblib/ray_backend.py — batches of joblib callables run as
tasks; results come back through the object store)."""

from __future__ import annotations

from typing import Any, Callable

import ray_tpu

try:
    from joblib._parallel_backends import SequentialBackend
    from joblib.parallel import ParallelBackendBase
except ImportError:  # pragma: no cover - joblib not installed
    ParallelBackendBase = object
    SequentialBackend = None


class _Result:
    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout=None):
        return ray_tpu.get(self._ref, timeout=timeout)


class RayBackend(ParallelBackendBase):
    """Each joblib batch (a callable returning a list) becomes one task."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def configure(self, n_jobs: int = 1, parallel=None, **_kwargs) -> int:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs: int) -> int:
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        total = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None or n_jobs < 0:
            return total
        return min(n_jobs, total)  # n_jobs=1 stays sequential, as in joblib

    def apply_async(self, func: Callable, callback=None) -> Any:
        @ray_tpu.remote
        def run_batch():
            return func()

        ref = run_batch.remote()
        result = _Result(ref)
        if callback is not None:
            import threading

            def wait_and_call():
                try:
                    callback(result.get())
                except BaseException:
                    pass

            threading.Thread(target=wait_and_call, daemon=True).start()
        return result

    def abort_everything(self, ensure_ready: bool = True) -> None:
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)
