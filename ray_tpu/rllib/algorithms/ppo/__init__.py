from ray_tpu.rllib.algorithms.ppo.multi_agent import (
    MultiAgentPPO, MultiAgentPPOConfig)
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig", "MultiAgentPPO", "MultiAgentPPOConfig"]
