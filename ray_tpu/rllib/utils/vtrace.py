"""V-trace off-policy correction (reference: rllib/algorithms/impala/
vtrace_torch.py; Espeholt 2018 IMPALA eq. 1).

Pure-JAX via ``lax.scan`` over the time axis — the whole correction stays
inside the jitted learner loss, no host round trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def vtrace(behavior_logp, target_logp, rewards, values, dones, bootstrap,
           gamma: float = 0.99, clip_rho: float = 1.0, clip_c: float = 1.0):
    """All inputs (T, B); bootstrap (B,). Returns (vs, pg_advantages).

    vs_t = V(x_t) + sum_k gamma^k (prod c) rho_k delta_k  computed as the
    standard backward recursion: acc_t = delta_t + gamma c_t acc_{t+1}.
    """
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), clip_rho)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), clip_c)
    not_done = 1.0 - dones

    next_values = jnp.concatenate([values[1:], bootstrap[None]], axis=0)
    # episode boundaries cut the bootstrap
    deltas = rho * (rewards + gamma * next_values * not_done - values)

    def backward(acc, inp):
        delta_t, c_t, nd_t = inp
        acc = delta_t + gamma * c_t * nd_t * acc
        return acc, acc

    _, acc_rev = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap),
        (deltas[::-1], c[::-1], not_done[::-1]))
    vs_minus_v = acc_rev[::-1]
    vs = values + vs_minus_v

    next_vs = jnp.concatenate([vs[1:], bootstrap[None]], axis=0)
    pg_adv = rho * (rewards + gamma * next_vs * not_done - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)
