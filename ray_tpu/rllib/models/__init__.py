from ray_tpu.rllib.models.catalog import (
    ConvModule, LSTMModule, get_module_for_space)

__all__ = ["ConvModule", "LSTMModule", "get_module_for_space"]
