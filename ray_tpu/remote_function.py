"""@ray_tpu.remote functions.

Parity with the reference (reference: ``python/ray/remote_function.py``):
``RemoteFunction`` wraps the user function, ``.remote(...)`` submits through
the core worker, ``.options(...)`` returns a per-call override view validated
the same way (reference: ``python/ray/_private/ray_option_utils.py``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import worker as worker_mod

_VALID_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "resources", "num_returns",
    "max_retries", "retry_exceptions", "scheduling_strategy", "name",
    "placement_group", "placement_group_bundle_index", "runtime_env",
    "memory", "_metadata",
}


def _resources_from_options(opts: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        resources["CPU"] = float(opts["num_cpus"])
    if opts.get("num_gpus") is not None:
        resources["GPU"] = float(opts["num_gpus"])
    if opts.get("num_tpus") is not None:
        resources["TPU"] = float(opts["num_tpus"])
    if opts.get("memory") is not None:
        resources["memory"] = float(opts["memory"])
    return resources


def validate_options(opts: Dict[str, Any]) -> None:
    for k in opts:
        if k not in _VALID_OPTIONS and k not in (
            "max_restarts", "max_task_retries", "max_concurrency", "lifetime",
            "namespace", "get_if_exists", "max_pending_calls",
        ):
            raise ValueError(f"invalid option '{k}'")
    nr = opts.get("num_returns")
    if nr is not None:
        if isinstance(nr, str):
            if nr not in ("streaming", "dynamic"):
                raise ValueError(
                    "num_returns must be an int >= 0, 'streaming', or "
                    f"'dynamic', got {nr!r}")
            opts["num_returns"] = -1  # wire sentinel for streaming
        elif nr < 0:
            raise ValueError("num_returns must be >= 0")
    num_tpus = opts.get("num_tpus")
    if num_tpus:
        from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

        ok, msg = TPUAcceleratorManager.validate_resource_request_quantity(num_tpus)
        if not ok:
            raise ValueError(msg)


class RemoteFunction:
    def __init__(self, function, **default_options):
        validate_options(default_options)
        self._function = function
        self._default_options = default_options
        functools.update_wrapper(self, function)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Remote functions cannot be called directly. "
            f"Use {self._function.__name__}.remote(...) instead."
        )

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def map(self, *iterables):
        """Vectorized submission (ISSUE 18): ``fn.map(xs)`` submits
        ``fn(x)`` for each x — ``builtins.map``/``zip`` semantics, so
        ``fn.map(xs, ys)`` submits ``fn(x, y)`` pairwise and stops at the
        shortest iterable (share a constant via ``itertools.repeat``).
        The whole batch is built in one pass through the driver
        (``Worker.submit_many``): one id block, one ownership
        registration, one trace stamp, one wire frame per destination.
        Returns one ObjectRef per call (a list of ref-lists when
        ``num_returns > 1``), in argument order."""
        return self._map(iterables, self._default_options)

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: dag/dag_node.py bind)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def options(self, **options):
        validate_options(options)
        merged = {**self._default_options, **options}
        parent = self

        class _Wrapped:
            def remote(self, *args, **kwargs):
                return parent._remote(args, kwargs, merged)

            def map(self, *iterables):
                return parent._map(iterables, merged)

            def bind(self, *args, **kwargs):
                from ray_tpu.dag import FunctionNode

                # self.remote already applies the merged options
                return FunctionNode(self, args, kwargs)

            def __getattr__(self, item):
                return getattr(parent, item)

        return _Wrapped()

    def _remote(self, args, kwargs, opts):
        w = worker_mod.global_worker
        if w is None or not w.connected:
            raise RuntimeError(
                "ray_tpu.init() must be called before invoking remote functions"
            )
        refs = w.submit_task(
            self._function,
            args,
            kwargs,
            num_returns=opts.get("num_returns", 1),
            resources=_resources_from_options(opts),
            max_retries=opts.get("max_retries", -1),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            scheduling_strategy=opts.get("scheduling_strategy"),
            placement_group=_resolve_pg(opts),
            placement_group_bundle_index=_resolve_pg_bundle_index(opts),
            runtime_env=opts.get("runtime_env"),
            name=opts.get("name", ""),
        )
        if opts.get("num_returns", 1) == -1:
            return refs  # ObjectRefGenerator
        if opts.get("num_returns", 1) == 1:
            return refs[0]
        return refs

    def _map(self, iterables, opts):
        w = worker_mod.global_worker
        if w is None or not w.connected:
            raise RuntimeError(
                "ray_tpu.init() must be called before invoking remote functions"
            )
        num_returns = opts.get("num_returns", 1)
        if num_returns == -1:
            raise ValueError("map() does not support streaming tasks")
        args_list = list(zip(*iterables)) if iterables else []
        batches = w.submit_many(
            self._function,
            args_list,
            num_returns=num_returns,
            resources=_resources_from_options(opts),
            max_retries=opts.get("max_retries", -1),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            scheduling_strategy=opts.get("scheduling_strategy"),
            placement_group=_resolve_pg(opts),
            placement_group_bundle_index=_resolve_pg_bundle_index(opts),
            runtime_env=opts.get("runtime_env"),
            name=opts.get("name", ""),
        )
        if num_returns == 1:
            return [refs[0] for refs in batches]
        return batches

    @property
    def underlying_function(self):
        return self._function


def _resolve_pg(opts):
    strategy = opts.get("scheduling_strategy")
    if strategy is not None and type(strategy).__name__ == "PlacementGroupSchedulingStrategy":
        return strategy.placement_group
    return opts.get("placement_group")


def _resolve_pg_bundle_index(opts) -> int:
    strategy = opts.get("scheduling_strategy")
    if (strategy is not None
            and type(strategy).__name__ == "PlacementGroupSchedulingStrategy"
            and opts.get("placement_group_bundle_index") is None):
        return strategy.placement_group_bundle_index
    idx = opts.get("placement_group_bundle_index")
    return -1 if idx is None else idx
