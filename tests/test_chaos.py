"""Chaos tests (reference: python/ray/tests/chaos/ + the
test_utils.py:1431 resource killers): inject worker/node failures WHILE
a workload runs and assert the recovery machinery — task retries, actor
restarts, node-death detection — delivers correct results anyway."""

import time

import pytest

import ray_tpu
from ray_tpu.util.chaos import NodeKiller, WorkerKiller, kill_random_node


def test_tasks_survive_worker_killer():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    try:
        @ray_tpu.remote(max_retries=5)
        def slow_square(x):
            time.sleep(0.3)
            return x * x

        killer = WorkerKiller(interval_s=0.4, max_kills=3, seed=1).run()
        try:
            refs = [slow_square.remote(k) for k in range(24)]
            results = ray_tpu.get(refs, timeout=240)
        finally:
            kills = killer.stop()
        assert sorted(results) == sorted(k * k for k in range(24))
        # the killer must actually have hit something for this to be a
        # chaos test rather than a happy-path run
        assert len(kills) >= 1, "WorkerKiller never found a target"
    finally:
        ray_tpu.shutdown()


def test_actor_restarts_under_worker_killer():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    try:
        @ray_tpu.remote(max_restarts=10, max_task_retries=10)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                time.sleep(0.2)
                return self.n

        counter = Counter.remote()
        assert ray_tpu.get(counter.bump.remote(), timeout=60) == 1
        killer = WorkerKiller(interval_s=0.5, max_kills=2, seed=2).run()
        try:
            values = [ray_tpu.get(counter.bump.remote(), timeout=120)
                      for _ in range(12)]
        finally:
            kills = killer.stop()
        # a restart resets in-memory state; values must stay positive and
        # the last call must have landed on a live incarnation
        assert all(v >= 1 for v in values)
        assert ray_tpu.get(counter.bump.remote(), timeout=120) >= 1
        assert len(kills) >= 1
    finally:
        ray_tpu.shutdown()


def test_node_killer_marks_node_dead():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=1)
        ray_tpu.init(_node=cluster.head_node)
        cluster.wait_for_nodes()
        assert sum(1 for n in ray_tpu.nodes() if n["alive"]) == 2
        record = kill_random_node(cluster)
        assert record and record.startswith("node ")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sum(1 for n in ray_tpu.nodes() if n["alive"]) == 1:
                break
            time.sleep(0.5)
        assert sum(1 for n in ray_tpu.nodes() if n["alive"]) == 1
        # the cluster still schedules work after losing the node
        @ray_tpu.remote
        def ping():
            return "ok"

        assert ray_tpu.get(ping.remote(), timeout=60) == "ok"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_node_killer_periodic_against_fleet():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=1)
        ray_tpu.init(_node=cluster.head_node)
        cluster.wait_for_nodes()
        killer = NodeKiller(cluster, interval_s=0.5, max_kills=2,
                            seed=3).run()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(killer.kills) < 2:
            time.sleep(0.3)
        kills = killer.stop()
        assert len(kills) == 2, kills
        # head survives; cluster functional
        @ray_tpu.remote
        def ping():
            return 1

        assert ray_tpu.get(ping.remote(), timeout=60) == 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
