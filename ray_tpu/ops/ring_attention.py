"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference has NO sequence/context parallelism (SURVEY §2.5 — repo-wide
grep for ring attention / Ulysses is empty); long-context is delegated to
external frameworks. Here it is first-class: q/k/v are sharded along the
``seq`` mesh axis, kv chunks rotate around the ICI ring via
``lax.ppermute``, and each hop folds into an online softmax — so memory per
chip is O(S/N) while the result is exact.

Call under ``shard_map`` (or from a jit whose shardings put S on ``seq``):
per-device shapes q [B, S_loc, H, D], k/v [B, S_loc, KVH, D].

Overlap note: XLA overlaps the ppermute DMA of step j+1 with the compute of
step j when latency hiding is enabled (standard on TPU); the loop is written
so kv for the next step is sent before the current block's math is consumed.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, scale, causal):
    """One blockwise attention: returns (unnormalized out, m, l) in fp32.

    q [B,Sq,H,D], k/v [B,Sk,KVH,D]; offsets are global position offsets.
    """
    from ray_tpu.ops.attention import _repeat_kv

    B, Sq, H, D = q.shape
    n_rep = H // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_off + jnp.arange(Sq)
        k_pos = k_off + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m == -inf → p would be exp(0)=1; zero them.
    p = jnp.where((m > _NEG_INF / 2)[..., None], p, 0.0)
    m = jnp.maximum(m, _NEG_INF)
    l = jnp.sum(p, axis=-1)                                   # [B,H,Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)   # [B,Sq,H,D]
    return o.astype(jnp.float32), m, l


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, axis_name: str = "seq", causal: bool = True,
) -> jax.Array:
    """Exact attention with kv rotating around the ``axis_name`` ring."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, S_loc, H, D = q.shape
    scale = D ** -0.5
    q_off = my_idx * S_loc

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, j):
        k_cur, v_cur, m_acc, l_acc, o_acc = carry
        src = (my_idx - j) % axis_size
        # Send kv onward immediately so the DMA overlaps the block compute.
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        o_blk, m_blk, l_blk = _block_attn(
            q, k_cur, v_cur, q_off, src * S_loc, scale, causal)
        m_new = jnp.maximum(m_acc, m_blk)
        a_old = jnp.exp(m_acc - m_new)
        a_blk = jnp.exp(m_blk - m_new)
        l_new = l_acc * a_old + l_blk * a_blk
        o_new = (o_acc * a_old.transpose(0, 2, 1)[..., None]
                 + o_blk * a_blk.transpose(0, 2, 1)[..., None])
        return (k_nxt, v_nxt, m_new, l_new, o_new), None

    m0 = jnp.full((B, H, S_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S_loc), jnp.float32)
    o0 = jnp.zeros((B, S_loc, H, D), jnp.float32)
    (k_f, v_f, m_f, l_f, o_f), _ = lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(axis_size))
    out = o_f / jnp.maximum(l_f, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)
