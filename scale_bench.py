"""Control-plane scale benchmarks (VERDICT r3 next #4).

Mirrors the reference's scalability-envelope suite
(reference: release/benchmarks/README.md:5-31 — many_tasks 10k,
many_actors 10k, many_pgs 1k, 1M queued tasks) scaled to one box: the
numbers prove the asyncio control plane schedules/queues at envelope
depth without wedging; absolute rates are bounded by this box's single
core (the baseline's came from a 64-core head + cluster).

Run: ``python scale_bench.py [--quick]`` — prints one JSON dict.
"""

from __future__ import annotations

import argparse
import json
import time


def bench_many_tasks(ray, n: int, quick: bool = False) -> dict:
    """n short tasks submitted at once: end-to-end completion rate
    (reference: many_tasks — 10k tasks across the cluster).

    Also the flight-recorder disabled-path gate (ISSUE 14): the
    instrumentation sites on the submit→reply hot path must cost ~zero
    when ``task_event_sample_rate=0`` (the default this phase runs
    under). The gate is deterministic — it times the ACTUAL disabled
    guard (``events.overhead_probe``), multiplies by the per-task site
    count, and asserts the total against the measured per-task budget —
    instead of differencing two noisy end-to-end runs."""

    @ray.remote
    def noop():
        return None

    ray.get(noop.remote(), timeout=120)
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    submitted = time.perf_counter() - t0
    ray.get(refs, timeout=600)
    total = time.perf_counter() - t0
    out = {"n": n, "submit_s": round(submitted, 3),
           "total_s": round(total, 3),
           "tasks_per_s": round(n / total, 1)}
    from ray_tpu._private import events as _ev
    from ray_tpu._private.config import CONFIG as _cfg

    # ~8 disabled-guard hits per task round trip today (submit root
    # check, record-event tc check, lease_wait, dispatch, worker-side
    # exec/arg/return guards, reply flush check); 2x headroom
    sites_per_task = 16
    guard_ns = _ev.overhead_probe(100_000)
    per_task_us = total / n * 1e6
    overhead_pct = guard_ns * sites_per_task / 1000.0 / per_task_us * 100
    out["events_disabled"] = {
        "sample_rate": float(_cfg.task_event_sample_rate),
        "guard_ns_per_site": round(guard_ns, 1),
        "sites_per_task_budgeted": sites_per_task,
        "overhead_pct_of_task": round(overhead_pct, 4),
    }
    if quick:
        assert overhead_pct < 2.0, (
            f"flight-recorder disabled path costs {overhead_pct:.2f}% of "
            f"a many_tasks round trip (guard {guard_ns:.0f}ns x "
            f"{sites_per_task} sites vs {per_task_us:.0f}us/task) — the "
            "ISSUE 14 hard requirement is <2%")

    # batched variant (ISSUE 18): the same n tasks through fn.map — one
    # id block / registration batch / wire frame, and ONE submit_batch::
    # root span instead of n roots when tracing is armed. The guard gate
    # re-asserts against the batched per-task time: the fast path makes
    # tasks CHEAPER, which makes the fixed guard cost a LARGER fraction,
    # so the <2% budget must be re-proven here, not assumed.
    @ray.remote
    def noop_b(i):
        return None

    ray.get(noop_b.remote(0), timeout=120)
    t0 = time.perf_counter()
    refs = noop_b.map(range(n))
    submitted_b = time.perf_counter() - t0
    ray.get(refs, timeout=600)
    total_b = time.perf_counter() - t0
    per_task_us_b = total_b / n * 1e6
    overhead_pct_b = (guard_ns * sites_per_task / 1000.0
                      / per_task_us_b * 100)
    out["batched"] = {
        "submit_s": round(submitted_b, 3),
        "submit_us_per_task": round(submitted_b / n * 1e6, 1),
        "total_s": round(total_b, 3),
        "tasks_per_s": round(n / total_b, 1),
        "overhead_pct_of_task": round(overhead_pct_b, 4),
    }
    if quick:
        assert overhead_pct_b < 2.0, (
            f"flight-recorder disabled path costs {overhead_pct_b:.2f}% "
            f"of a BATCHED many_tasks round trip (guard {guard_ns:.0f}ns "
            f"x {sites_per_task} sites vs {per_task_us_b:.0f}us/task) — "
            "the fast path must not push the guard budget over 2%")
    return out


def bench_many_actors(ray, n: int) -> dict:
    """n actors created + first call answered, then killed
    (reference: many_actors — launch rate)."""

    @ray.remote
    class A:
        def ping(self):
            return 1

    # alive actors hold ~no CPU (reference actors hold 0 CPU while
    # idle); the envelope measures control-plane depth, not core count
    t0 = time.perf_counter()
    actors = [A.options(num_cpus=0.001).remote() for _ in range(n)]
    ray.get([a.ping.remote() for a in actors], timeout=600)
    ready = time.perf_counter() - t0
    for a in actors:
        ray.kill(a)
    return {"n": n, "ready_s": round(ready, 3),
            "actors_per_s": round(n / ready, 1)}


def bench_actor_scale(quick: bool) -> dict:
    """First-class actor scale-out phase (ISSUE 10): burst and
    incremental-batch creation rates, tail rate over the last 10%,
    straggler count, and the warm-pool hit ratio — run in its OWN
    cluster so the warm pool can be sized for the phase and a flake
    can't poison the shared-cluster phases."""
    import os
    import ray_tpu

    os.environ.setdefault("RAY_TPU_WORKER_POOL_WARM_TARGET",
                          "16" if quick else "32")
    # a 1,000-worker boot storm on a 2-core box can starve the agent's
    # heartbeats past the default 15s budget — the node is busy, not
    # dead; the chaos phases keep the tight threshold
    os.environ.setdefault("RAY_TPU_HEALTH_CHECK_FAILURE_THRESHOLD", "40")
    ray_tpu.init(num_cpus=4)
    out: dict = {}
    try:
        @ray_tpu.remote
        class A:
            def ping(self):
                return 1

        def pool_stats():
            from ray_tpu._private import worker as wm

            w = wm.global_worker
            return w._acall(
                w.agent.call("GetWorkerPoolStats", {}, timeout=10),
                timeout=15)

        def run_round(n: int, straggler_timeout: float) -> dict:
            t0 = time.perf_counter()
            actors = [A.options(num_cpus=0.001).remote() for _ in range(n)]
            submit_s = time.perf_counter() - t0
            refs = [a.ping.remote() for a in actors]
            t90 = t100 = None
            deadline = time.perf_counter() + straggler_timeout
            pending = list(refs)
            ready_n = 0
            while pending and time.perf_counter() < deadline:
                done, pending = ray_tpu.wait(
                    pending, num_returns=len(pending), timeout=0.25)
                ready_n += len(done)
                now = time.perf_counter()
                if t90 is None and ready_n >= 0.9 * n:
                    t90 = now - t0
                if ready_n >= n:
                    t100 = now - t0
            stragglers = len(pending)
            total = t100 if t100 is not None else straggler_timeout
            res = {
                "n": n, "submit_s": round(submit_s, 3),
                "ready_s": round(total, 3),
                "actors_per_s": round(n / total, 1),
                "stragglers": stragglers,
            }
            if t90 is not None and t100 is not None and t100 > t90:
                res["tail_rate_90_100_per_s"] = round(
                    (n - int(0.9 * n)) / (t100 - t90), 1)
            for a in actors:
                ray_tpu.kill(a)
            return res

        before = pool_stats()
        # burst: everything at once (the many_actors shape)
        out["burst"] = run_round(200 if quick else 1000,
                                 straggler_timeout=300 if quick else 900)
        # incremental: batches of 50 against the refilling pool — the
        # sustained-rate shape serve autoscaling produces
        batches = []
        for _ in range(4 if quick else 8):
            batches.append(run_round(50, straggler_timeout=120))
            time.sleep(1.0)  # refill window between batches
        out["incremental"] = {
            "batch_n": 50,
            "rates_per_s": [b["actors_per_s"] for b in batches],
            "stragglers": sum(b["stragglers"] for b in batches),
        }
        if not quick:
            # scale envelope: 5,000 actors created and answering
            out["envelope"] = run_round(5000, straggler_timeout=1800)
        after = pool_stats()
        hits = after["hits"] - before["hits"]
        demand_hits = (after.get("demand_hits", 0)
                       - before.get("demand_hits", 0))
        misses = after["misses"] - before["misses"]
        served = hits + demand_hits
        hit_ratio = (round(served / (served + misses), 3)
                     if served + misses else 0.0)
        out["pool"] = {
            "warm_target": after["warm_target"],
            "hits": hits, "demand_hits": demand_hits, "misses": misses,
            "hit_ratio": hit_ratio,
            "refills": after["refills"] - before["refills"],
            "ready_batch_hist": after["ready_batch_hist"],
            "lease_batch_hist": after["lease_batch_hist"],
        }
        # direct-call plane transport columns (ISSUE 11): the driver's
        # own mux/shm counters — every same-node actor call above rode
        # (or deliberately fell back from) the shm doorbell lane
        from ray_tpu._private.mux import MUX_STATS
        from ray_tpu._private.shm_rpc import stats_snapshot

        shm = stats_snapshot()
        out["transport"] = {
            "mux_sessions_opened": MUX_STATS["sessions_opened"],
            "mux_streams_opened": MUX_STATS["streams_opened"],
            "shm_frames_out": shm["calls_out"],
            "shm_frames_in": shm["frames_in"],
            "shm_attach_ok": shm["attach_ok"],
            "shm_fallback_oversize": shm["fallback_oversize"],
            "shm_fallback_ring_full": shm["fallback_ring_full"],
            "order_gap_flushes": shm["order_gap_flushes"],
        }
        # the predictive refill exists to make bursts pool-served: a
        # quick run falling under 0.5 is a regression, fail loudly
        # (ISSUE 11 satellite; pre-PR baseline was 0.17)
        if quick and served + misses >= 100:
            assert hit_ratio >= 0.5, (
                f"warm-pool hit_ratio {hit_ratio} < 0.5: {out['pool']}")
    finally:
        ray_tpu.shutdown()
        from ray_tpu._private import lifecycle

        lifecycle.gc_stale_sessions()
    return out


def bench_pg_churn(ray, n: int) -> dict:
    """create -> ready -> remove cycles (reference: placement group
    create/removal 899/s on m4.16xlarge). Warmed: the first ~50 cycles
    pay one-time costs (connection ramp, code paths); the recorded
    number is steady-state like the baseline's."""
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)

    for _ in range(min(50, n)):
        pg = placement_group([{"CPU": 1}])
        assert pg.wait(timeout_seconds=60)
        remove_placement_group(pg)
    t0 = time.perf_counter()
    for _ in range(n):
        pg = placement_group([{"CPU": 1}])
        assert pg.wait(timeout_seconds=60)
        remove_placement_group(pg)
    took = time.perf_counter() - t0
    return {"n": n, "total_s": round(took, 3),
            "pg_cycles_per_s": round(n / took, 1)}


def bench_many_pgs(ray, n: int) -> dict:
    """n placement groups simultaneously alive (reference envelope: 1,000
    simultaneous PGs). Zero-CPU bundles: the envelope tests control-plane
    bookkeeping depth, not this box's 4 CPUs."""
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)

    t0 = time.perf_counter()
    pgs = [placement_group([{"CPU": 0.001}]) for _ in range(n)]
    for pg in pgs:
        assert pg.wait(timeout_seconds=120)
    created = time.perf_counter() - t0
    for pg in pgs:
        remove_placement_group(pg)
    return {"n": n, "create_all_s": round(created, 3),
            "pgs_per_s": round(n / created, 1)}


def bench_queued_tasks(ray, n: int) -> dict:
    """Queue-depth envelope: n tasks pending behind a blocked worker pool
    (reference envelope: 1M queued). Proves submission + queueing stays
    O(1) per task and the runtime drains the backlog without wedging."""
    import ray_tpu

    @ray.remote
    class Gate:
        def __init__(self):
            self._open = False

        def open(self):
            self._open = True

        def is_open(self):
            return self._open

    gate = Gate.remote()

    @ray.remote
    def blocked(gate):
        import time as _t
        while not ray_tpu.get(gate.is_open.remote()):
            _t.sleep(0.2)
        return 1

    @ray.remote
    def noop():
        return None

    # fill every worker slot with blockers, then queue n tasks behind them
    blockers = [blocked.remote(gate) for _ in range(4)]
    time.sleep(1.0)
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    submit_s = time.perf_counter() - t0
    # backlog is fully queued; release the gate and drain everything
    gate.open.remote()
    t1 = time.perf_counter()
    ray.get(refs, timeout=1200)
    drain_s = time.perf_counter() - t1
    ray.get(blockers, timeout=60)
    return {"n": n, "submit_s": round(submit_s, 3),
            "enqueue_per_s": round(n / submit_s, 1),
            "drain_s": round(drain_s, 3),
            "drain_per_s": round(n / drain_s, 1)}


def bench_compiled_dag(ray, n: int) -> dict:
    """Compiled vs eager DAG repeat execution (VERDICT r4 #1: ≥5× at 1 KB
    and 10 MB through a 3-stage pipeline; reference:
    python/ray/dag/compiled_dag_node.py:141 channel-based execution)."""
    import numpy as np
    from ray_tpu.dag import InputNode

    @ray.remote
    def stage_a(x):
        return x

    @ray.remote
    def stage_b(x):
        return x

    @ray.remote
    def stage_c(x):
        return x

    out = {}
    for label, elems in (("1kb", 256), ("10mb", 10 * 1024 * 1024 // 4)):
        payload = np.zeros(elems, dtype=np.float32)
        with InputNode() as inp:
            dag = stage_c.bind(stage_b.bind(stage_a.bind(inp)))
        iters = n if label == "1kb" else max(5, n // 10)
        ray.get(dag.execute(payload), timeout=120)  # warm leases
        t0 = time.perf_counter()
        for _ in range(iters):
            ray.get(dag.execute(payload), timeout=120)
        eager_s = time.perf_counter() - t0
        compiled = dag.experimental_compile()
        try:
            compiled.execute(payload).get(timeout=120)  # warm loops
            t0 = time.perf_counter()
            for _ in range(iters):
                compiled.execute(payload).get(timeout=120)
            compiled_s = time.perf_counter() - t0
        finally:
            compiled.teardown()
        out[label] = {
            "iters": iters,
            "eager_ms_per_exec": round(eager_s / iters * 1000, 3),
            "compiled_ms_per_exec": round(compiled_s / iters * 1000, 3),
            "speedup": round(eager_s / compiled_s, 2),
        }
    return out


def bench_cross_node(quick: bool = False) -> dict:
    """Two-node (localhost) transfer-plane trajectory: GB/s pulling 64 MB
    and 256 MB objects produced on the far node, a batched-get probe
    (8 refs, one `get`), and the pulling agent's chunk/stripe/budget
    counters. The ``window1`` mode forces the pull pipeline window to 1 —
    the pre-pipeline sequential-chunk behavior — so the pipelined speedup
    stays a tracked number, not a one-off claim."""
    import os

    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    out = {}
    for mode, env in (("window1", {"RAY_TPU_OBJECT_PULL_WINDOW": "1"}),
                      ("pipelined", {})):
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        cluster = None
        try:
            cluster = Cluster(initialize_head=True,
                              head_node_args={"num_cpus": 2})
            ray_tpu.init(_node=cluster.head_node)
            cluster.add_node(num_cpus=2, resources={"far": 4})
            cluster.wait_for_nodes()

            @ray_tpu.remote(resources={"far": 0.01})
            def produce(mb):
                return np.ones(mb * 1024 * 1024 // 8, np.float64)

            sizes = [64] if (quick or mode == "window1") else [64, 256]
            res = {}
            for mb in sizes:
                times = []
                for _ in range(3):  # first pull pays channel setup; best
                    ref = produce.remote(mb)  # ~= steady state
                    # wait() observes the seal without pulling; get()
                    # below times the transfer alone
                    ray_tpu.wait([ref], num_returns=1, timeout=120)
                    t0 = time.perf_counter()
                    val = ray_tpu.get(ref, timeout=600)
                    times.append(time.perf_counter() - t0)
                    assert val.nbytes == mb * 1024 * 1024
                    del val, ref
                res[f"pull_{mb}mb"] = {
                    "seconds": [round(t, 4) for t in times],
                    "first_gb_per_s": round(mb / 1024 / times[0], 3),
                    "best_gb_per_s": round(mb / 1024 / min(times), 3)}
            if mode == "pipelined":
                refs = [produce.remote(8) for _ in range(8)]
                ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
                t0 = time.perf_counter()
                vals = ray_tpu.get(refs, timeout=600)
                res["batched_get_8x8mb_s"] = round(
                    time.perf_counter() - t0, 4)
                del vals, refs
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            res["pull_stats"] = w._acall(w.agent.call("GetPullStats", {}))
            out[mode] = res
        finally:
            ray_tpu.shutdown()
            if cluster is not None:
                cluster.shutdown()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    return out


def bench_broadcast(quick: bool = False) -> dict:
    """Weight-broadcast trajectory (device object plane, ISSUE 9): one
    64 MB object distributed to N consumer nodes. ``tree`` mode runs the
    spanning broadcast tree (chunk-level relay, fanout 2); the
    ``serial`` comparator (broadcast disabled) pulls consumer-by-
    consumer — the N-serial-point-to-point baseline the tree exists to
    beat. Reports per-consumer latency, aggregate GB/s (N * size /
    wall), tree shape counters, and the zero-copy put counter proving
    the producer's put skipped pickle entirely."""
    import os

    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    size_mb = 64
    counts = [1, 4] if quick else [1, 2, 4, 8]
    # Capped mode: every node's chunk serving rides a simulated per-node
    # uplink (``object_serve_bandwidth_bytes_ps`` — a sleep-based token
    # bucket, identical in both modes). Loopback numbers are CPU-bound
    # (every process shares the same cores, so topology cannot show);
    # the cap restores the constraint broadcast trees exist to beat:
    # the root's upload capacity. 30 MB/s keeps the per-chunk pacing
    # slot well above this box's scheduler jitter. Under an uplink-bound
    # model the bench runs the tree at fanout 1 — the bandwidth-optimal
    # chain (the root uploads the object once; every hop relays while
    # receiving) — where the default fanout 2 trades a little root
    # bandwidth for half the depth.
    cap_bytes_ps = 30 * 1024 * 1024
    out = {"object_mb": size_mb, "serve_bandwidth_cap_bytes_ps": cap_bytes_ps}

    def run(mode: str, n_consumers: int, capped: bool = False) -> dict:
        env = {"RAY_TPU_BCAST_ENABLED": "1" if mode == "tree" else "0",
               "RAY_TPU_BCAST_FANOUT": "1" if capped else "2",
               "RAY_TPU_OBJECT_SERVE_BANDWIDTH_BYTES_PS":
                   str(cap_bytes_ps) if capped else "0"}
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        cluster = None
        try:
            cluster = Cluster(
                initialize_head=True,
                head_node_args={"num_cpus": 2, "resources": {"src": 4}})
            ray_tpu.init(_node=cluster.head_node)
            nodes = [cluster.add_node(num_cpus=1,
                                      resources={f"far{i}": 1})
                     for i in range(n_consumers)]
            cluster.wait_for_nodes()

            @ray_tpu.remote(resources={"src": 1})
            def produce():
                return np.ones(size_mb * 1024 * 1024 // 8, np.float64)

            def consumer(i, drop_copy=False):
                @ray_tpu.remote(resources={f"far{i}": 1})
                def consume(wrapped):
                    import time as _t

                    import ray_tpu as _rt
                    from ray_tpu._private import worker as worker_mod

                    t0 = _t.perf_counter()
                    arr = _rt.get(wrapped[0], timeout=600)
                    dt = _t.perf_counter() - t0
                    w = worker_mod.global_worker
                    stats = w._acall(w.agent.call("GetPullStats", {}))
                    nbytes = arr.nbytes
                    if drop_copy:
                        # serial comparator semantics: N independent
                        # POINT-TO-POINT pulls from the producer — drop
                        # this node's copy so the next consumer cannot
                        # stripe across it (that swarm effect is the
                        # transfer plane's own optimization, not the
                        # baseline under test)
                        del arr
                        w._acall(w.agent.call(
                            "FreeObjects", {"ids": [wrapped[0].hex()]}))
                    return {"seconds": dt, "nbytes": nbytes,
                            "depth": stats["bcast_tree_depth"],
                            "relay_bytes": stats["bcast_relay_bytes"],
                            "tree_pulls": stats["bcast_tree_pulls"],
                            "fallbacks": stats["bcast_fallbacks"]}

                return consume

            # warm the consumer workers so the measured window is the
            # transfer, not N cold worker boots
            warm = ray_tpu.put(np.zeros(1))
            ray_tpu.get([consumer(i).remote([warm])
                         for i in range(n_consumers)], timeout=120)

            ledger_base = _ledger_probe()
            ref = produce.remote()
            ray_tpu.wait([ref], num_returns=1, timeout=120)
            t0 = time.perf_counter()
            if mode == "serial":
                results = [ray_tpu.get(
                    consumer(i, drop_copy=True).remote([ref]), timeout=600)
                    for i in range(n_consumers)]
            else:
                results = ray_tpu.get(
                    [consumer(i).remote([ref])
                     for i in range(n_consumers)], timeout=600)
            wall = time.perf_counter() - t0
            assert all(r["nbytes"] == size_mb * 1024 * 1024
                       for r in results)
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            head_stats = w._acall(w.agent.call("GetPullStats", {}))
            lat = sorted(r["seconds"] for r in results)
            result = {
                "consumers": n_consumers,
                "wall_s": round(wall, 4),
                "aggregate_gb_per_s": round(
                    n_consumers * size_mb / 1024 / wall, 3),
                "consumer_latency_s": {
                    "min": round(lat[0], 4), "max": round(lat[-1], 4),
                    "mean": round(sum(lat) / len(lat), 4)},
                "depth_max": max(r["depth"] for r in results),
                "relay_bytes": sum(r["relay_bytes"] for r in results),
                "tree_pulls": sum(r["tree_pulls"] for r in results),
                "fallbacks": sum(r["fallbacks"] for r in results),
                "zero_copy_puts": head_stats["zero_copy_puts"],
            }
            # ledger hygiene (ISSUE 15): dropping the broadcast ref must
            # return the driver ledger + head store to their pre-run
            # counts — a broadcast whose refs outlive it is a leak
            del ref
            result["post_run_ledger"] = _ledger_drain(ledger_base)
            assert result["post_run_ledger"]["drained"], (
                f"broadcast {mode} leaked past the run: "
                f"{result['post_run_ledger']}")
            return result
        finally:
            ray_tpu.shutdown()
            if cluster is not None:
                cluster.shutdown()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    for n in counts:
        out[f"tree_{n}"] = run("tree", n)
    comparator_n = 4
    out[f"serial_{comparator_n}"] = run("serial", comparator_n)
    if out[f"tree_{comparator_n}"].get("aggregate_gb_per_s") and \
            out[f"serial_{comparator_n}"].get("aggregate_gb_per_s"):
        out["loopback_speedup"] = round(
            out[f"tree_{comparator_n}"]["aggregate_gb_per_s"]
            / out[f"serial_{comparator_n}"]["aggregate_gb_per_s"], 2)
    # the topology claim: tree vs N serial pulls under a per-node uplink
    out[f"capped_tree_{comparator_n}"] = run(
        "tree", comparator_n, capped=True)
    out[f"capped_serial_{comparator_n}"] = run(
        "serial", comparator_n, capped=True)
    if not quick:
        out["capped_tree_8"] = run("tree", 8, capped=True)
    tree = out[f"capped_tree_{comparator_n}"]
    serial = out[f"capped_serial_{comparator_n}"]
    if tree.get("aggregate_gb_per_s") and serial.get("aggregate_gb_per_s"):
        out["tree_vs_serial_speedup"] = round(
            tree["aggregate_gb_per_s"] / serial["aggregate_gb_per_s"], 2)
    return out


def bench_chaos(quick: bool = False) -> dict:
    """Recovery-latency trajectory (robustness budget, tracked like a
    perf number): node-death detection time under a one-way partition
    (no RST), pending-call fail-fast time for a driver blocked on the
    dead node, fenced-agent exit time after the partition heals, and
    actor restart time after a SIGKILL. Tight detection budget via env
    so the phase stays in seconds."""
    import os
    import signal as _signal

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.exceptions import (
        ActorDiedError, NodeDiedError, RayActorError)
    from ray_tpu.util.chaos import NetworkPartitioner

    env = {"RAY_TPU_FAULT_INJECTION": "1",
           "RAY_TPU_HEALTH_CHECK_PERIOD_MS": "500",
           "RAY_TPU_HEALTH_CHECK_FAILURE_THRESHOLD": "4",
           "RAY_TPU_NODE_DISCONNECT_GRACE_S": "2.0"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    out = {"health_budget_s": 2.0}
    cluster = partitioner = None
    try:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
        ray_tpu.init(_node=cluster.head_node)
        node = cluster.add_node(num_cpus=2, resources={"far": 4})
        cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"far": 0.01})
        class FarProbe:
            def ping(self):
                return "pong"

            def stall(self, seconds):
                import time as _t

                _t.sleep(seconds)
                return "done"

        probe = FarProbe.remote()
        ray_tpu.get(probe.ping.remote(), timeout=120)
        pending = probe.stall.remote(600)
        partitioner = NetworkPartitioner(cluster, mode="out")
        t0 = time.perf_counter()
        partitioner.partition(node.node_id)
        deadline = t0 + 60
        while time.perf_counter() < deadline and any(
                n["node_id"] == node.node_id and n["alive"]
                for n in ray_tpu.nodes()):
            time.sleep(0.05)
        out["node_death_detection_s"] = round(time.perf_counter() - t0, 3)
        try:
            ray_tpu.get(pending, timeout=60)
            out["pending_call_failfast_s"] = None  # should not happen
        except (ActorDiedError, NodeDiedError, RayActorError):
            out["pending_call_failfast_s"] = round(
                time.perf_counter() - t0, 3)
        t1 = time.perf_counter()
        partitioner.heal(node.node_id)
        while time.perf_counter() - t1 < 90 and \
                node.agent_proc.poll() is None:
            time.sleep(0.1)
        out["fenced_agent_exit_s"] = (
            round(time.perf_counter() - t1, 3)
            if node.agent_proc.poll() is not None else None)

        # actor restart latency: SIGKILL the (local) actor worker, time
        # until the restarted incarnation answers
        @ray_tpu.remote(max_restarts=4, max_task_retries=4)
        class LocalProbe:
            def pid(self):
                return os.getpid()

        lp = LocalProbe.remote()
        victim_pid = ray_tpu.get(lp.pid.remote(), timeout=120)
        t2 = time.perf_counter()
        os.kill(victim_pid, _signal.SIGKILL)
        while time.perf_counter() - t2 < 120:
            try:
                if ray_tpu.get(lp.pid.remote(), timeout=10) != victim_pid:
                    break
            except Exception:
                time.sleep(0.1)
        out["actor_restart_s"] = round(time.perf_counter() - t2, 3)

        # lineage reconstruction latency (ISSUE 17): lose every copy of
        # owned plasma objects with their node, time until get() hands
        # back the replayed values — and the counter must move
        import numpy as _np

        cluster.remove_node(node)  # fenced earlier; drop from the roster
        lnode = cluster.add_node(num_cpus=2, resources={"lin": 4})
        cluster.wait_for_nodes()

        @ray_tpu.remote(max_retries=2, resources={"lin": 1})
        def lin_produce(i):
            return _np.full(200_000, i, _np.int64)

        refs = [lin_produce.remote(i) for i in range(2)]
        ray_tpu.wait(refs, num_returns=len(refs), timeout=120)
        from ray_tpu._private import worker as _wm

        recon_before = _wm.global_worker._lineage.reconstructions
        cluster.remove_node(lnode)
        cluster.add_node(num_cpus=2, resources={"lin": 4})
        cluster.wait_for_nodes()
        time.sleep(2.5)  # node-death detection lag
        t3 = time.perf_counter()
        vals = ray_tpu.get(refs, timeout=120)
        out["lineage_reconstruction_s"] = round(
            time.perf_counter() - t3, 3)
        out["lineage_reconstructions"] = (
            _wm.global_worker._lineage.reconstructions - recon_before)
        assert out["lineage_reconstructions"] > 0, (
            "node kill replayed nothing through lineage: "
            "ray_tpu_lineage_reconstructions_total stayed 0")
        assert all(int(v[0]) == i for i, v in enumerate(vals))
        del refs, vals
    finally:
        if partitioner is not None:
            partitioner.heal()
        ray_tpu.shutdown()
        if cluster is not None:
            cluster.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def bench_head_chaos(quick: bool = False) -> dict:
    """Durable-head-plane chaos (ISSUE 8; ROADMAP item 2): kill -9 the
    GCS at random points while an actor workload, a KV write stream and a
    serve deployment run. Asserts the WAL + recovery-reconciliation
    contract: zero loss of live actors, every ACKED kv put readable
    after the last restart, actor-table fidelity (all workers ALIVE, no
    ghosts, nothing reconciled dead), and recovery time under budget."""
    import os
    import tempfile
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.exceptions import HeadUnavailableError, RayTpuError
    from ray_tpu.experimental import internal_kv
    from ray_tpu.util.chaos import HeadKiller

    persist = os.path.join(tempfile.mkdtemp(prefix="head_chaos_"),
                           "head_state.bin")
    env = {"RAY_TPU_GCS_PERSIST": persist,
           "RAY_TPU_HEAD_WATCHDOG_PERIOD_S": "0.5",
           "RAY_TPU_HEAD_PING_TIMEOUT_S": "2.0",
           "RAY_TPU_GCS_RECOVERY_GRACE_S": "5.0",
           "RAY_TPU_GCS_OUTAGE_QUEUE_S": "20.0"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    recovery_budget_s = 15.0
    n_actors = 4 if quick else 8
    kills = 2 if quick else 3
    out = {"kills": kills, "actors": n_actors,
           "recovery_budget_s": recovery_budget_s}
    cluster = killer = None
    try:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 4})
        ray_tpu.init(_node=cluster.head_node)

        @ray_tpu.remote(num_cpus=0.01)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

            def value(self):
                return self.n

        actors = [Counter.options(name=f"hc-{i}",
                                  lifetime="detached").remote()
                  for i in range(n_actors)]
        ray_tpu.get([a.bump.remote() for a in actors], timeout=120)

        @serve.deployment(num_replicas=1, max_ongoing_requests=16)
        class Echo:
            def __call__(self, x):
                return x

        handle = serve.run(Echo.bind(), name="headchaos",
                           route_prefix="/headchaos")
        assert handle.remote(1).result(timeout_s=60) == 1

        stop = threading.Event()
        stats = {"bumps": [0] * n_actors, "kv_acked": 0,
                 "serve_ok": 0, "serve_err": 0,
                 "head_unavailable": 0, "workload_err": 0}
        lock = threading.Lock()

        def actor_client(i):
            # direct worker connections: actor calls must keep completing
            # THROUGH head outages, not merely recover afterwards
            while not stop.is_set():
                try:
                    ray_tpu.get(actors[i].bump.remote(), timeout=60)
                    with lock:
                        stats["bumps"][i] += 1
                except Exception:
                    with lock:
                        stats["workload_err"] += 1
                stop.wait(0.05)

        def kv_client():
            k = 0
            while not stop.is_set():
                try:
                    internal_kv._internal_kv_put(
                        b"hc-%d" % k, b"v-%d" % k)
                    with lock:
                        stats["kv_acked"] += 1  # acked => must survive
                    k += 1
                except HeadUnavailableError:
                    with lock:
                        stats["head_unavailable"] += 1
                except (RayTpuError, ConnectionError, TimeoutError):
                    with lock:
                        stats["workload_err"] += 1
                stop.wait(0.05)

        def serve_client():
            j = 0
            while not stop.is_set():
                try:
                    assert handle.remote(j).result(timeout_s=60) == j
                    with lock:
                        stats["serve_ok"] += 1
                except Exception:
                    with lock:
                        stats["serve_err"] += 1
                j += 1
                stop.wait(0.05)

        threads = [threading.Thread(target=actor_client, args=(i,))
                   for i in range(n_actors)]
        threads += [threading.Thread(target=kv_client),
                    threading.Thread(target=serve_client)]
        for t in threads:
            t.start()

        killer = HeadKiller(cluster, downtime_s=0.75, interval_s=4.0,
                            max_kills=kills, seed=7, persist=persist)
        killer.run()
        deadline = time.perf_counter() + 120
        while len(killer.kills) < kills and time.perf_counter() < deadline:
            time.sleep(0.25)
        kill_records = killer.stop()
        t_rec0 = time.perf_counter()
        stop.set()
        for t in threads:
            t.join(timeout=90)

        # ---- recovery: every actor answers + KV serves reads again ----
        recovered = None
        while time.perf_counter() - t_rec0 < 120:
            try:
                vals = ray_tpu.get([a.value.remote() for a in actors],
                                   timeout=30)
                internal_kv._internal_kv_get(b"hc-0")
                recovered = time.perf_counter() - t_rec0
                break
            except Exception:
                time.sleep(0.25)
        out["head_kills"] = kill_records
        out["recovery_s"] = (round(recovered, 3)
                             if recovered is not None else None)
        out["recovery_under_budget"] = (recovered is not None
                                        and recovered < recovery_budget_s)

        # ---- zero actor loss + counter fidelity -----------------------
        vals = ray_tpu.get([a.value.remote() for a in actors], timeout=60)
        expected = [stats["bumps"][i] + 1 for i in range(n_actors)]
        # an unacked bump may still have landed (kill between execute and
        # reply): counters may exceed acked, never trail them
        out["actor_counters_intact"] = all(
            v >= e for v, e in zip(vals, expected))
        out["actors_lost"] = sum(
            1 for v, e in zip(vals, expected) if v < e)

        # ---- KV fidelity: every ACKED put is readable ------------------
        missing = 0
        for k in range(stats["kv_acked"]):
            if internal_kv._internal_kv_get(b"hc-%d" % k) != b"v-%d" % k:
                missing += 1
        out["kv_acked"] = stats["kv_acked"]
        out["kv_lost"] = missing

        # ---- actor-table fidelity + reconciliation verdict -------------
        # the table re-converges when the agent's re-register claims the
        # RECOVERING actors — time that as its own recovery metric
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        t_claim0 = time.perf_counter()
        alive = 0
        while time.perf_counter() - t_claim0 < 60:
            views = {v["name"]: v for v in w.head_call("ListActors", {})}
            alive = sum(1 for i in range(n_actors)
                        if views.get(f"hc-{i}", {}).get("state") == "ALIVE")
            if alive == n_actors:
                break
            time.sleep(0.25)
        out["actor_table_alive"] = alive
        out["table_reclaim_s"] = round(time.perf_counter() - t_rec0, 3)
        status = w.head_call("GetHeadStatus", {})
        out["head_incarnation"] = status["incarnation"]
        out["wal"] = status["wal"]
        out["reconciled_dead"] = (status.get("last_recovery") or {}).get(
            "reconciled_dead", 0)
        out["serve"] = {"ok": stats["serve_ok"], "err": stats["serve_err"]}
        out["head_unavailable_typed"] = stats["head_unavailable"]
        out["workload_err"] = stats["workload_err"]
        out["pass"] = bool(
            out["recovery_under_budget"]
            and out["actors_lost"] == 0
            and out["kv_lost"] == 0
            and out["actor_table_alive"] == n_actors)
        serve.delete("headchaos")
        serve.shutdown()
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        if cluster is not None:
            cluster.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        from ray_tpu._private import lifecycle

        lifecycle.gc_stale_sessions()
    return out


def bench_serve_load(quick: bool = False) -> dict:
    """Serving-plane load phase (ISSUE 6; ROADMAP item 1): sustained
    multi-client RPS against a deployed app, tracked across rounds like
    MFU is. Reports (a) continuous-batching engine vs static @serve.batch
    throughput on the same mixed-length generative workload, (b) RPS +
    p50/p99 latency + shed rate + autoscale reaction/drain time under
    sustained overload, and (c) a chaos variant — SIGKILL one replica
    mid-load — proving the phase completes with no hang and no unshed
    request lost."""
    import functools
    import os
    import signal as _signal
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.exceptions import BackPressureError, RayTpuError

    # one hardware iteration for a whole batch costs STEP_S regardless of
    # occupancy (the XLA-compiled-step model), and ONE device runs ONE
    # batch at a time — the static path serializes its batches on a
    # simulated device lock exactly like the engine's stepper thread
    # serializes its steps. Mixed generation lengths are the workload that
    # makes static whole-request batching hold every slot hostage to the
    # longest member.
    STEP_S = 0.01
    LENS = [2, 3, 4, 6, 8, 12]

    @serve.deployment(num_replicas=1, max_ongoing_requests=32,
                      max_queued_requests=64)
    class StaticGen:
        def __init__(self, step_s):
            import asyncio

            self._step_s = step_s
            self._device = asyncio.Lock()

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.005)
        async def gen(self, items):
            import asyncio

            # whole-request batching: the batch occupies the device until
            # its LONGEST generation finishes; every short member waits
            async with self._device:
                await asyncio.sleep(self._step_s * max(items))
            return [n for n in items]

        async def __call__(self, n):
            return await self.gen(int(n))

    @serve.deployment(num_replicas=1, max_ongoing_requests=32,
                      max_queued_requests=64)
    class EngineGen:
        def __init__(self, step_s):
            import time as _t

            def step(mid, states):
                _t.sleep(step_s)  # one iteration for the whole batch
                res = [None] * len(states)
                for i, s in enumerate(states):
                    if s is None:
                        continue
                    s["i"] += 1
                    res[i] = (s["i"], s["i"] >= s["n"])
                return res

            self.engine = serve.ContinuousBatchingEngine(
                step, prefill_fn=lambda p, m: {"n": int(p), "i": 0},
                max_batch_size=8, allowed_batch_sizes=(2, 4, 8),
                name="bench")

        def pid(self):
            return os.getpid()

        def generate(self, n):
            # non-streaming endpoint: iteration-level batching on the
            # device without paying one chunk round-trip per token
            return list(self.engine.submit(int(n)))

        def __call__(self, n):
            yield from self.engine.submit(int(n))

    def drive(issue, seconds, clients, counters, latencies):
        """Closed-loop clients; ``issue(n)`` returns the token count."""
        stop = time.perf_counter() + seconds
        lock = threading.Lock()

        def client(seed):
            k = seed
            while time.perf_counter() < stop:
                n = LENS[k % len(LENS)]
                k += 1
                t0 = time.perf_counter()
                try:
                    toks = issue(n)
                except BackPressureError:
                    with lock:
                        counters["shed"] += 1
                        counters["issued"] += 1
                    time.sleep(0.01)  # client-owned backoff
                    continue
                except (RayTpuError, ConnectionError, TimeoutError):
                    with lock:
                        counters["typed_errors"] += 1
                        counters["issued"] += 1
                    continue
                with lock:
                    counters["issued"] += 1
                    counters["completed"] += 1
                    counters["tokens"] += toks
                    latencies.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=seconds + 120)
        return time.perf_counter() - t0

    def pctl(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1, int(q * len(vals)))], 4)

    out = {"step_s": STEP_S}
    load_s = 6.0 if quick else 10.0
    clients = 8
    ray_tpu.init(num_cpus=4)
    try:
        serve.start(http_options={"port": 0})

        # -- (a) static @serve.batch vs continuous engine, same workload --
        def static_issue(n, _h=None):
            return _h.remote(n).result(timeout_s=120)

        def engine_issue(n, _h=None):
            return len(_h.generate.remote(n).result(timeout_s=120))

        for label, app, mk_issue in (
                ("static_batch", StaticGen.bind(STEP_S), static_issue),
                ("engine", EngineGen.bind(STEP_S), engine_issue)):
            handle = serve.run(app, name=label, route_prefix=f"/{label}")
            issue = functools.partial(mk_issue, _h=handle)
            counters = {"issued": 0, "completed": 0, "tokens": 0,
                        "shed": 0, "typed_errors": 0}
            lat = []
            issue(2)  # warm the route + (for the engine) the stepper
            took = drive(issue, load_s, 16, counters, lat)
            out[label] = {
                "gens_per_s": round(counters["completed"] / took, 1),
                "tokens_per_s": round(counters["tokens"] / took, 1),
                "p50_s": pctl(lat, 0.50), "p99_s": pctl(lat, 0.99),
                "shed": counters["shed"],
            }
            serve.delete(label)
        if out["static_batch"]["tokens_per_s"]:
            out["engine_speedup"] = round(
                out["engine"]["tokens_per_s"]
                / out["static_batch"]["tokens_per_s"], 2)

        # -- (b) sustained overload: autoscale up, shed typed, drain ------
        # per-replica capacity (4 running + 4 queued) is deliberately under
        # the 16-client offered load: one replica MUST shed typed
        # backpressure until the autoscaler absorbs the demand
        auto = EngineGen.options(
            max_ongoing_requests=4, max_queued_requests=4,
            autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                                "target_ongoing_requests": 2.0,
                                "upscale_delay_s": 0.5,
                                "downscale_delay_s": 1.0}).bind(STEP_S)
        handle = serve.run(auto, name="autoload", route_prefix="/autoload")
        replicas_seen = []
        mon_stop = threading.Event()

        def monitor():
            while not mon_stop.is_set():
                st = serve.status("autoload")["deployments"].get(
                    "EngineGen", {})
                replicas_seen.append(
                    (time.perf_counter(), st.get("replicas", 0)))
                time.sleep(0.2)

        mon = threading.Thread(target=monitor)
        mon.start()
        counters = {"issued": 0, "completed": 0, "tokens": 0,
                    "shed": 0, "typed_errors": 0}
        lat = []

        def stream_issue(n, _h=handle):
            return len(list(_h.options(stream=True).remote(n)))

        t_load0 = time.perf_counter()
        took = drive(stream_issue, load_s * 1.5, 16, counters, lat)
        scale_up = next((t - t_load0 for t, r in replicas_seen if r > 1),
                        None)
        t_drain0 = time.perf_counter()
        drained = None
        while time.perf_counter() - t_drain0 < 90:
            st = serve.status("autoload")["deployments"].get("EngineGen", {})
            if st.get("replicas") == 1 and st.get("target_replicas") == 1:
                drained = time.perf_counter() - t_drain0
                break
            time.sleep(0.5)
        mon_stop.set()
        mon.join(timeout=10)
        out["serve_overload"] = {
            "clients": 16, "duration_s": round(took, 1),
            "rps": round(counters["completed"] / took, 1),
            "p50_s": pctl(lat, 0.50), "p99_s": pctl(lat, 0.99),
            "shed": counters["shed"],
            "shed_rate": round(counters["shed"]
                               / max(1, counters["issued"]), 3),
            "typed_errors": counters["typed_errors"],
            "lost": counters["issued"] - counters["completed"]
            - counters["shed"] - counters["typed_errors"],
            "peak_replicas": max((r for _, r in replicas_seen), default=1),
            "autoscale_reaction_s": (round(scale_up, 2)
                                     if scale_up is not None else None),
            "drain_to_min_s": (round(drained, 2)
                               if drained is not None else None),
        }
        serve.delete("autoload")

        # -- (c) chaos variant: SIGKILL a replica mid-load ----------------
        chaos_app = EngineGen.options(num_replicas=2).bind(STEP_S)
        handle = serve.run(chaos_app, name="chaosload",
                           route_prefix="/chaosload")
        victim = handle.pid.remote().result(timeout_s=60)
        counters = {"issued": 0, "completed": 0, "tokens": 0,
                    "shed": 0, "typed_errors": 0}
        lat = []
        killer_fired = []

        def killer():
            time.sleep(load_s / 3)
            os.kill(victim, _signal.SIGKILL)
            killer_fired.append(time.perf_counter())

        def chaos_issue(n, _h=handle):
            return len(list(_h.options(stream=True).remote(n)))

        kt = threading.Thread(target=killer)
        kt.start()
        took = drive(chaos_issue, load_s, clients, counters, lat)
        kt.join(timeout=30)
        recovered = None
        t_rec0 = killer_fired[0] if killer_fired else time.perf_counter()
        while time.perf_counter() - t_rec0 < 90:
            st = serve.status("chaosload")["deployments"].get(
                "EngineGen", {})
            if st.get("replicas", 0) >= 2:
                recovered = time.perf_counter() - t_rec0
                break
            time.sleep(0.5)
        out["serve_chaos"] = {
            "rps": round(counters["completed"] / took, 1),
            "p50_s": pctl(lat, 0.50), "p99_s": pctl(lat, 0.99),
            "shed": counters["shed"],
            "typed_errors_on_kill": counters["typed_errors"],
            "lost": counters["issued"] - counters["completed"]
            - counters["shed"] - counters["typed_errors"],
            "replica_replaced_s": (round(recovered, 2)
                                   if recovered is not None else None),
        }
        serve.delete("chaosload")
        serve.shutdown()
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        from ray_tpu._private import lifecycle

        lifecycle.gc_stale_sessions()
    return out


def _ledger_probe() -> dict:
    """Driver owned-ref count + head-node store bytes (ISSUE 15): the
    baseline every exchange must return to once its refs drop."""
    import gc

    from ray_tpu._private import worker as wm

    gc.collect()
    w = wm.global_worker
    rc = w.reference_counter
    with rc._lock:
        owned = len(rc._owned)
    store = w._acall(w.agent.call("GetStoreStats", {}, timeout=15),
                     timeout=20)
    return {"owned": owned, "store_used": int(store.get("used", 0))}


def _ledger_drain(base: dict, timeout: float = 30.0) -> dict:
    """Poll until the ledger returns to ``base`` (frees ride async
    RPCs). Catches the PR 12 'shard refs stay owned for the exchange's
    lifetime' contract ever outliving the exchange."""
    import gc
    import time as _t

    deadline = _t.monotonic() + timeout
    cur = _ledger_probe()
    while (cur["owned"] > base["owned"]
           or cur["store_used"] > base["store_used"]) \
            and _t.monotonic() < deadline:
        gc.collect()
        _t.sleep(0.25)
        cur = _ledger_probe()
    return {
        "owned_delta": cur["owned"] - base["owned"],
        "store_bytes_delta": cur["store_used"] - base["store_used"],
        "drained": (cur["owned"] <= base["owned"]
                    and cur["store_used"] <= base["store_used"]),
    }


def bench_data_shuffle(quick: bool = False) -> dict:
    """Streaming multi-node shuffle trajectory (ISSUE 12).

    Layout isolates what is being measured: input blocks are DRIVER-put
    (head store), maps pinned to the head ("src"), reducers pinned to
    the 3 consumer nodes ("red") — so agent bytes_fetched deltas count
    the EXCHANGE's movement, not incidental task placement. ``streaming``
    (per-shard zero-copy outputs, pipelined reduce) is compared against
    ``materialize`` (the legacy AllToAll exchange: every reducer pulls
    every map output) on identical clusters; the O(M+R)-vs-O(M×R)
    claim is the measured pull_ratio. A chaos variant kills -9 one
    shard-holding node mid-shuffle and checks byte-identical completion
    with re-execution counters > 0.
    """
    import hashlib
    import os

    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.chaos import DaemonKiller

    M = R = 8
    rows_per = 512
    width = 512 if quick else 4096  # 1 MB or 8 MB blocks
    dataset_bytes = M * rows_per * (width * 4 + 8)

    def make_blocks():
        rng = np.random.default_rng(2026)
        return [{"id": np.arange(i * rows_per, (i + 1) * rows_per),
                 "x": rng.random((rows_per, width)).astype(np.float32)}
                for i in range(M)]

    def rows_sha(ds):
        acc = []
        for batch in ds.iter_batches(batch_size=None, prefetch_batches=0):
            ids = np.asarray(batch["id"])
            xs = np.ascontiguousarray(np.asarray(batch["x"]))
            for i in range(len(ids)):
                acc.append((int(ids[i]),
                            hashlib.sha256(xs[i].tobytes()).hexdigest()))
        acc.sort()
        return hashlib.sha256(str(acc).encode()).hexdigest()

    def node_pull_stats(i):
        @ray_tpu.remote(resources={f"red{i}": 0.001})
        def probe():
            from ray_tpu._private import worker as wm

            w = wm.global_worker
            return w._acall(w.agent.call("GetPullStats", {}))

        return ray_tpu.get(probe.remote(), timeout=120)

    def cluster_pull_totals():
        """Reducer-node pulls = the exchange's own movement (maps are
        head-local to the driver-put inputs, and the driver's pulls of
        the OUTPUT blocks ride the head agent, reported separately)."""
        from ray_tpu._private import worker as wm

        w = wm.global_worker
        head = w._acall(w.agent.call("GetPullStats", {}))
        nodes = [node_pull_stats(i) for i in range(3)]
        return {
            "bytes_fetched": sum(s["bytes_fetched"] for s in nodes),
            "head_bytes_fetched": head["bytes_fetched"],
            "zero_copy_puts": (head["zero_copy_puts"]
                               + sum(s["zero_copy_puts"] for s in nodes)),
        }

    out = {"dataset_mb": round(dataset_bytes / 1024 / 1024, 2),
           "maps": M, "reducers": R}
    shas = {}
    for mode in ("streaming", "materialize"):
        cluster = None
        try:
            cluster = Cluster(
                initialize_head=True,
                head_node_args={"num_cpus": 4, "resources": {"src": 100}})
            ray_tpu.init(_node=cluster.head_node)
            for i in range(3):
                cluster.add_node(num_cpus=2, resources={f"red{i}": 100,
                                                        "red": 100})
            cluster.wait_for_nodes()
            import ray_tpu.data as rd
            from ray_tpu.data.context import DataContext

            ctx = DataContext.get_current()
            ctx.streaming_shuffle = mode == "streaming"
            ctx.shuffle_map_remote_args = {"resources": {"src": 0.001}}
            ctx.shuffle_reduce_remote_args = {"resources": {"red": 0.001}}
            before = cluster_pull_totals()
            ledger_base = _ledger_probe()
            ds = rd.from_blocks(make_blocks()).random_shuffle(
                seed=11, num_blocks=R)
            t0 = time.perf_counter()
            shas[mode] = rows_sha(ds)
            wall = time.perf_counter() - t0
            after = cluster_pull_totals()
            pulled = after["bytes_fetched"] - before["bytes_fetched"]
            rec = {
                "wall_s": round(wall, 3),
                "gb_per_s": round(dataset_bytes / 1024 ** 3 / wall, 4),
                "bytes_pulled_mb": round(pulled / 1024 / 1024, 2),
                "pull_ratio": round(pulled / dataset_bytes, 3),
                "consume_pulled_mb": round(
                    (after["head_bytes_fetched"]
                     - before["head_bytes_fetched"]) / 1024 / 1024, 2),
                "zero_copy_puts": (after["zero_copy_puts"]
                                   - before["zero_copy_puts"]),
            }
            if mode == "streaming":
                st = ds._last_stats.to_dict()
                rec["loop_iters"] = st["loop_iters"]
                rec["consumer_stall_s"] = st["consumer_stall_s"]
                for op in st["ops"]:
                    ex = op.get("extra") or {}
                    if "shuffle_maps" in ex:
                        rec["stall_fraction"] = ex["shuffle_stall_fraction"]
                        rec["reduce_overlapped_maps"] = \
                            ex["shuffle_reduce_overlapped_maps"]
                        rec["inflight_peak_mb"] = round(
                            ex["shuffle_inflight_peak_bytes"] / 1024
                            / 1024, 2)
            # ledger hygiene (ISSUE 15): once the dataset is dropped,
            # every shard ref the exchange held must drain and the store
            # must return to its pre-run byte count
            del ds
            rec["post_run_ledger"] = _ledger_drain(ledger_base)
            assert rec["post_run_ledger"]["drained"], (
                f"{mode} shuffle leaked past its exchange: "
                f"{rec['post_run_ledger']}")
            out[mode] = rec
        finally:
            ray_tpu.shutdown()
            if cluster is not None:
                cluster.shutdown()
            from ray_tpu._private import lifecycle

            lifecycle.gc_stale_sessions()
    out["byte_identical"] = shas.get("streaming") == shas.get("materialize")
    out["criteria"] = {
        "pull_ratio_lt_1_5": out["streaming"]["pull_ratio"] < 1.5,
        "materialize_ratio": out["materialize"]["pull_ratio"],
        "stall_fraction_lt_0_5":
            out["streaming"].get("stall_fraction", 1.0) < 0.5,
        "zero_copy_puts_gt_0": out["streaming"]["zero_copy_puts"] > 0,
    }

    # chaos variant: kill -9 one shard-holding node mid-shuffle
    cluster = None
    try:
        os.environ["RAY_TPU_PULL_DEAD_HOLDER_ROUNDS"] = "3"
        os.environ["RAY_TPU_OBJECT_PULL_DEADLINE_S"] = "90"
        cluster = Cluster(
            initialize_head=True,
            head_node_args={"num_cpus": 2, "resources": {"safe": 100}})
        ray_tpu.init(_node=cluster.head_node)
        nodes = [cluster.add_node(num_cpus=2, resources={"vic": 100})
                 for _ in range(2)]
        cluster.wait_for_nodes()
        import ray_tpu.data as rd
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        ctx.streaming_shuffle = True
        ctx.shuffle_map_remote_args = {"resources": {"vic": 0.001}}
        ctx.shuffle_reduce_remote_args = {"resources": {"safe": 0.001}}
        from ray_tpu._private import worker as _wm

        recon_before = _wm.global_worker._lineage.reconstructions
        ledger_base = _ledger_probe()
        ds = rd.from_blocks(make_blocks()).random_shuffle(
            seed=11, num_blocks=R)
        t0 = time.perf_counter()
        acc = []
        killed = False
        it = ds.iter_batches(batch_size=None, prefetch_batches=0)
        import hashlib as _h
        for batch in it:
            ids = np.asarray(batch["id"])
            xs = np.ascontiguousarray(np.asarray(batch["x"]))
            for i in range(len(ids)):
                acc.append((int(ids[i]),
                            _h.sha256(xs[i].tobytes()).hexdigest()))
            if not killed:
                killed = True
                killer = DaemonKiller(cluster.session_dir,
                                      roles=("agent",), max_kills=1)
                killer.kill_target(
                    {"role": "agent", "pid": nodes[0].agent_proc.pid})
        acc.sort()
        sha = _h.sha256(str(acc).encode()).hexdigest()
        extras = {}
        for op in ds._last_stats.to_dict()["ops"]:
            if "shuffle_maps" in (op.get("extra") or {}):
                extras = op["extra"]
        # replayed map bodies must stay light: the surviving victim
        # node's workers just re-executed maps via lineage — jax must
        # not have been warmed in them (ISSUE 17 satellite)
        @ray_tpu.remote(resources={"vic": 0.001})
        def jax_probe():
            import sys as _s

            return "jax" in _s.modules

        jax_clean = ray_tpu.get(jax_probe.remote(), timeout=60) is False
        reconstructions = (_wm.global_worker._lineage.reconstructions
                           - recon_before)
        out["chaos"] = {
            "wall_s": round(time.perf_counter() - t0, 3),
            "rows": len(acc),
            "byte_identical": sha == shas.get("streaming"),
            "map_reexecs": extras.get("shuffle_map_reexecs", 0),
            "reduce_retries": extras.get("shuffle_reduce_retries", 0),
            "lineage_reconstructions": reconstructions,
            "jax_unimported_in_replay_workers": jax_clean,
        }
        assert reconstructions > 0, (
            "node kill replayed nothing through lineage: "
            "ray_tpu_lineage_reconstructions_total stayed 0")
        assert jax_clean, "lineage replay warmed jax in a map worker"
        # ownership ledger (ISSUE 15) must still drain to zero delta
        # once the dataset drops, replays and all. The loop vars hold
        # zero-copy views of the LAST batch — a live view pins its
        # arena object, which would read as a leaked reduce output here.
        del ds, it, batch, ids, xs
        out["chaos"]["post_run_ledger"] = _ledger_drain(ledger_base)
        assert out["chaos"]["post_run_ledger"]["drained"], (
            f"chaos shuffle leaked past its exchange: "
            f"{out['chaos']['post_run_ledger']}")
    except Exception as e:  # noqa: BLE001 — chaos flake keeps main phases
        out["chaos"] = {"error": f"{type(e).__name__}: {e}"}
    finally:
        os.environ.pop("RAY_TPU_PULL_DEAD_HOLDER_ROUNDS", None)
        os.environ.pop("RAY_TPU_OBJECT_PULL_DEADLINE_S", None)
        ray_tpu.shutdown()
        if cluster is not None:
            cluster.shutdown()
        from ray_tpu._private import lifecycle

        lifecycle.gc_stale_sessions()
    return out


def bench_train_elastic(quick: bool = False) -> dict:
    """Elastic-training recovery trajectory (ISSUE 20, tracked like a
    perf number): steady steps/s of a paced data-parallel run, then a
    DaemonKiller SIGKILLs one train worker mid-epoch — time-to-resume
    (kill → first post-resume result, from the ``train_resume::total``
    span) and post-resume steps/s ride the artifact next to the steady
    rate, so a detection or restart regression shows up as a diff."""
    import os
    import tempfile

    import ray_tpu
    from ray_tpu.train import (
        FailureConfig, InStoreCheckpoint, JaxTrainer, RunConfig,
        ScalingConfig)
    from ray_tpu.util.chaos import DaemonKiller

    steps = 80 if quick else 200
    pace_s = 0.02

    def loop(config):
        import pickle as _pickle

        import numpy as np
        from ray_tpu import train as _train

        ctx = _train.get_context()
        rank = ctx.get_world_rank()
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8)
        y = X @ rng.randn(8)
        w = np.zeros(8)
        start = 0
        ckpt = _train.get_checkpoint()
        if isinstance(ckpt, InStoreCheckpoint):
            st = _pickle.loads(bytes(ckpt.get_file("state.pkl")))
            start, w = st["step"] + 1, st["w"]
        for step in range(start, config["steps"]):
            w = w - 0.05 * (2.0 * X.T @ (X @ w - y) / len(y))
            if config.get("pid_file") and rank == 1 and step >= 10 \
                    and not os.path.exists(config["pid_file"]):
                with open(config["pid_file"], "w") as f:
                    f.write(str(os.getpid()))
            time.sleep(config["pace_s"])
            _train.report(
                {"step": step, "resumed_from": start},
                checkpoint=InStoreCheckpoint.from_state(
                    {"state.pkl": _pickle.dumps(
                        {"step": step, "w": w})}, step=step))

    def fit(name, tmp, pid_file=None):
        return JaxTrainer(
            loop,
            train_loop_config={"steps": steps, "pace_s": pace_s,
                               "pid_file": pid_file},
            scaling_config=ScalingConfig(num_workers=2,
                                         resources_per_worker={"CPU": 1}),
            run_config=RunConfig(
                name=name, storage_path=tmp,
                failure_config=FailureConfig(max_failures=3)),
        ).fit()

    out = {"steps": steps, "pace_s": pace_s, "world_size": 2}
    # the recovery breakdown rides train_resume:: flight-recorder spans,
    # which the default sample_rate=0 would drop
    saved_rate = os.environ.get("RAY_TPU_TASK_EVENT_SAMPLE_RATE")
    os.environ["RAY_TPU_TASK_EVENT_SAMPLE_RATE"] = "1.0"
    ray_tpu.init(num_cpus=4)
    killer = None
    try:
        with tempfile.TemporaryDirectory() as tmp:
            t0 = time.perf_counter()
            clean = fit("steady", tmp)
            steady_wall = time.perf_counter() - t0
            assert clean.error is None, clean.error
            out["steady_steps_per_s"] = round(steps / steady_wall, 2)

            pid_file = os.path.join(tmp, "victim_pid")
            kill_at = {}

            def victim(rec):
                try:
                    with open(pid_file) as f:
                        hit = rec["pid"] == int(f.read())
                except (OSError, ValueError):
                    return False
                if hit:
                    kill_at["t"] = time.perf_counter()
                return hit

            from ray_tpu._private.worker import global_worker

            killer = DaemonKiller(global_worker.session_dir,
                                  roles=("worker",), interval_s=0.1,
                                  max_kills=1, filter_fn=victim)
            killer.run()
            t1 = time.perf_counter()
            chaos = fit("chaos", tmp, pid_file=pid_file)
            chaos_wall = time.perf_counter() - t1
            assert chaos.error is None, chaos.error
            assert killer.kills, "chaos kill never fired"
            out["restarts"] = chaos.restarts
            out["kills"] = list(killer.kills)
            out["resumed_from_step"] = chaos.metrics.get("resumed_from")

            # recovery breakdown from the flight recorder
            w = global_worker
            w.flush_task_events(wait=True)
            spans = w.head_call("ListSpans", {"limit": 20000},
                                timeout=10) or []
            resume = {}
            for sp in spans:
                name = str(sp.get("name", ""))
                if name.startswith("train_resume::"):
                    part = name.split("::", 1)[1]
                    resume[part] = round(
                        max(resume.get(part, 0.0),
                            (sp.get("dur_us") or 0) / 1e6), 3)
            out["resume_spans_s"] = resume
            out["time_to_resume_s"] = resume.get("total")
            # steps the restarted incarnation ran, over the wall time it
            # had after the kill + resume window
            if kill_at and resume.get("total") is not None:
                resumed_from = chaos.metrics.get("resumed_from") or 0
                post_wall = (t1 + chaos_wall) - kill_at["t"] \
                    - resume["total"]
                if post_wall > 0:
                    out["post_resume_steps_per_s"] = round(
                        (steps - resumed_from) / post_wall, 2)
            from ray_tpu.util import metrics as _metrics

            m = _metrics._REGISTRY.get("ray_tpu_train_restarts_total")
            out["restarts_counter"] = (
                sum(v for _, v in m.snapshot()["values"]) if m else 0)
    finally:
        if killer is not None:
            killer.stop()
        if saved_rate is None:
            os.environ.pop("RAY_TPU_TASK_EVENT_SAMPLE_RATE", None)
        else:
            os.environ["RAY_TPU_TASK_EVENT_SAMPLE_RATE"] = saved_rate
        ray_tpu.shutdown()
        from ray_tpu._private import lifecycle

        lifecycle.gc_stale_sessions()
    return out


def main(quick: bool = False) -> dict:
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        results = {}
        results["many_tasks"] = bench_many_tasks(
            ray_tpu, 2000 if quick else 10_000, quick=quick)
        results["many_actors"] = bench_many_actors(
            ray_tpu, 200 if quick else 1000)
        results["pg_churn"] = bench_pg_churn(ray_tpu, 50 if quick else 200)
        results["many_pgs"] = bench_many_pgs(ray_tpu, 200 if quick else 1000)
        results["queued_tasks"] = bench_queued_tasks(
            ray_tpu, 20_000 if quick else 100_000)
        results["compiled_dag"] = bench_compiled_dag(
            ray_tpu, 20 if quick else 50)
    finally:
        # leak gate: even a partial run must not leave daemons/shm
        # segments behind to starve the next benchmark
        ray_tpu.shutdown()
        from ray_tpu._private import lifecycle

        lifecycle.gc_stale_sessions()
    # actor scale-out phase (ISSUE 10): own cluster (warm pool sized for
    # the phase), standalone artifact so the actor-creation trajectory
    # diffs across rounds like the other *_latest.json files
    try:
        results["actor_scale"] = bench_actor_scale(quick)
    except Exception as e:  # noqa: BLE001
        results["actor_scale"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import os

        if "error" not in results["actor_scale"]:
            art = os.environ.get("RAY_TPU_ACTORSCALE_OUT",
                                 "ACTORS_latest.json")
            with open(art, "w") as f:
                json.dump(results["actor_scale"], f, indent=2,
                          sort_keys=True)
    except Exception:
        pass
    # two-node phase builds (and tears down) its own localhost clusters; a
    # flake here must not discard the JSON of every completed phase above
    try:
        results["cross_node"] = bench_cross_node(quick)
    except Exception as e:  # noqa: BLE001 — partial results still print
        results["cross_node"] = {"error": f"{type(e).__name__}: {e}"}
    # broadcast phase (ISSUE 9): weight-distribution GB/s via the
    # spanning tree vs the N-serial-pulls comparator; standalone
    # artifact so the distribution trajectory diffs across rounds
    try:
        results["broadcast"] = bench_broadcast(quick)
    except Exception as e:  # noqa: BLE001
        results["broadcast"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import os

        # a failed phase must not clobber the previous round's artifact
        if "error" not in results["broadcast"]:
            art = os.environ.get("RAY_TPU_BCAST_OUT", "BCAST_latest.json")
            with open(art, "w") as f:
                json.dump(results["broadcast"], f, indent=2, sort_keys=True)
    except Exception:
        pass
    # chaos phase: recovery latencies tracked like a perf number, same
    # isolation story as cross_node (own cluster, flake-tolerant)
    try:
        results["chaos"] = bench_chaos(quick)
    except Exception as e:  # noqa: BLE001
        results["chaos"] = {"error": f"{type(e).__name__}: {e}"}
    # head-plane chaos phase (ISSUE 8): kill -9 the GCS mid-workload;
    # written standalone too so the durability trajectory diffs across
    # rounds like RAYPERF_rNN
    try:
        results["head_chaos"] = bench_head_chaos(quick)
    except Exception as e:  # noqa: BLE001
        results["head_chaos"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import os

        art = os.environ.get("RAY_TPU_HEADCHAOS_OUT",
                             "HEAD_CHAOS_latest.json")
        with open(art, "w") as f:
            json.dump(results["head_chaos"], f, indent=2, sort_keys=True)
    except Exception:
        pass
    # streaming-shuffle phase (ISSUE 12): own clusters per mode, written
    # standalone so the shuffle trajectory diffs across rounds
    try:
        results["data_shuffle"] = bench_data_shuffle(quick)
    except Exception as e:  # noqa: BLE001
        results["data_shuffle"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import os

        if "error" not in results["data_shuffle"]:
            art = os.environ.get("RAY_TPU_DATASHUFFLE_OUT",
                                 "DATA_SHUFFLE_latest.json")
            with open(art, "w") as f:
                json.dump(results["data_shuffle"], f, indent=2,
                          sort_keys=True)
    except Exception:
        pass
    # elastic-training phase (ISSUE 20): kill -9 a train worker
    # mid-epoch; steady vs time-to-resume vs post-resume rates, written
    # standalone so the recovery trajectory diffs across rounds
    try:
        results["train_elastic"] = bench_train_elastic(quick)
    except Exception as e:  # noqa: BLE001
        results["train_elastic"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import os

        if "error" not in results["train_elastic"]:
            art = os.environ.get("RAY_TPU_TRAINELASTIC_OUT",
                                 "TRAIN_ELASTIC_latest.json")
            with open(art, "w") as f:
                json.dump(results["train_elastic"], f, indent=2,
                          sort_keys=True)
    except Exception:
        pass
    # serving-plane phase (own cluster + serve control plane, same
    # flake-isolation story); its result is ALSO written standalone so the
    # serving trajectory is diffable across rounds like RAYPERF_rNN
    try:
        results["serve_load"] = bench_serve_load(quick)
    except Exception as e:  # noqa: BLE001
        results["serve_load"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import os

        art = os.environ.get("RAY_TPU_SERVELOAD_OUT",
                             "SERVE_LOAD_latest.json")
        with open(art, "w") as f:
            json.dump(results["serve_load"], f, indent=2, sort_keys=True)
    except Exception:
        pass
    print(json.dumps(results))
    try:
        from ray_tpu._private import lifecycle

        lifecycle.gc_stale_sessions()
    except Exception:
        pass
    return results


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
