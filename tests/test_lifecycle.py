"""Guaranteed-teardown gate (ISSUE 1): after any shutdown path — clean,
cluster, or chaotic — zero registered pids survive, zero session dirs
remain, and the driver's event loop dies without "Task was destroyed but
it is pending!" warnings. These are the leaks that turned the round-5
MULTICHIP gate red (22 orphan daemons + stale /dev/shm segments starving
the next run).
"""

import logging
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_all_dead(session_dir: str, timeout_s: float = 10.0):
    """Poll the registry until every registered pid is dead; returns the
    stragglers (empty list = success)."""
    from ray_tpu._private import lifecycle

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if not os.path.exists(session_dir):
            return []
        live = lifecycle.live_registered(session_dir)
        if not live:
            return []
        time.sleep(0.25)
    return lifecycle.live_registered(session_dir) \
        if os.path.exists(session_dir) else []


class _AsyncioWarnings(logging.Handler):
    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


@pytest.fixture
def asyncio_log():
    handler = _AsyncioWarnings()
    logger = logging.getLogger("asyncio")
    logger.addHandler(handler)
    yield handler
    logger.removeHandler(handler)


def test_shutdown_reaps_everything(asyncio_log):
    import ray_tpu
    from ray_tpu._private import lifecycle

    ray_tpu.init(num_cpus=2)
    node = ray_tpu._global_node
    session_dir = node.session_dir

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(4)]) == [1, 2, 3, 4]
    # daemons + at least one worker must be in the registry before stop
    roles = {r["role"] for r in lifecycle.live_registered(session_dir)}
    assert {"gcs", "agent"} <= roles, roles
    assert "worker" in roles, roles
    recorded = lifecycle.live_registered(session_dir)

    ray_tpu.shutdown()

    for rec in recorded:
        assert not lifecycle._pid_alive(rec["pid"], rec.get("create_time")), \
            f"{rec['role']} pid {rec['pid']} survived shutdown"
    assert not os.path.exists(session_dir), \
        "session dir (shm segments) survived shutdown"
    pending = [m for m in asyncio_log.messages if "pending" in m]
    assert not pending, pending


def test_cluster_teardown_reaps_everything():
    import ray_tpu
    from ray_tpu._private import lifecycle
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2)
    ray_tpu.init(_node=cluster.head_node)
    cluster.wait_for_nodes()
    session_dir = cluster.session_dir

    @ray_tpu.remote
    def g():
        return os.getpid()

    ray_tpu.get([g.remote() for _ in range(4)])
    recorded = lifecycle.live_registered(session_dir)
    assert len(recorded) >= 3  # gcs + 2 agents at minimum

    ray_tpu.shutdown()
    cluster.shutdown()

    for rec in recorded:
        assert not lifecycle._pid_alive(rec["pid"], rec.get("create_time")), \
            f"{rec['role']} pid {rec['pid']} survived cluster teardown"
    assert not os.path.exists(session_dir)


def test_driver_sigkill_fate_sharing():
    """SIGKILL the driver mid-workload: PDEATHSIG + the supervisor-poll
    watchdog must reap gcs/agent/forkserver/workers within 10s."""
    from ray_tpu._private import lifecycle

    driver_src = (
        "import ray_tpu, time\n"
        "ray_tpu.init(num_cpus=2)\n"
        "@ray_tpu.remote\n"
        "class A:\n"
        "    def ping(self): return 'ok'\n"
        "a = A.remote()\n"
        "assert ray_tpu.get(a.ping.remote()) == 'ok'\n"
        "print('READY', ray_tpu._global_node.session_dir, flush=True)\n"
        "time.sleep(600)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, "-c", driver_src],
                            stdout=subprocess.PIPE, text=True, env=env)
    session_dir = None
    try:
        deadline = time.monotonic() + 120
        for line in proc.stdout:
            if line.startswith("READY"):
                session_dir = line.split()[1]
                break
            if time.monotonic() > deadline:
                break
        assert session_dir, "driver never became ready"
        assert lifecycle.live_registered(session_dir), \
            "no registered daemons before the kill"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        stragglers = _wait_all_dead(session_dir, timeout_s=10.0)
        assert not stragglers, \
            f"daemons survived driver SIGKILL: {stragglers}"
    finally:
        if proc.poll() is None:
            proc.kill()
        if session_dir and os.path.exists(session_dir):
            lifecycle.reap_session(session_dir, remove=True)


def test_agent_sigkill_chaos_reaps_workers():
    """util.chaos.DaemonKiller SIGKILLs the node agent mid-workload: the
    agent's subtree (forkserver + workers) fate-shares with it and must
    die; shutdown() then reaps the rest of the session."""
    import ray_tpu
    from ray_tpu._private import lifecycle
    from ray_tpu.util.chaos import DaemonKiller

    ray_tpu.init(num_cpus=2)
    session_dir = ray_tpu._global_node.session_dir
    try:
        @ray_tpu.remote
        def h(x):
            return x * 2

        assert ray_tpu.get(h.remote(21)) == 42
        subtree = [r for r in lifecycle.live_registered(session_dir)
                   if r["role"] in ("agent", "forkserver", "worker")]
        assert subtree

        killer = DaemonKiller(session_dir, roles=("agent",),
                              interval_s=0.2, max_kills=1)
        killer.run()
        deadline = time.monotonic() + 10
        while not killer.kills and time.monotonic() < deadline:
            time.sleep(0.1)
        assert killer.stop(), "killer never found the agent"

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(not lifecycle._pid_alive(r["pid"], r.get("create_time"))
                   for r in subtree):
                break
            time.sleep(0.25)
        stragglers = [r for r in subtree
                      if lifecycle._pid_alive(r["pid"], r.get("create_time"))]
        assert not stragglers, \
            f"agent subtree survived agent SIGKILL: {stragglers}"
    finally:
        ray_tpu.shutdown()
    assert not os.path.exists(session_dir)


def test_compiled_dag_get_raises_on_dead_stage():
    """CompiledDAGRef.get(timeout=...) must raise within its timeout when
    a stage process is SIGKILL'd — not block forever."""
    import ray_tpu
    from ray_tpu.dag import InputNode

    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def stage(x):
            return (os.getpid(), x * 2)

        with InputNode() as inp:
            dag = stage.bind(inp)
        compiled = dag.experimental_compile()
        try:
            pid, v = compiled.execute(3).get(timeout=30)
            assert v == 6
            os.kill(pid, signal.SIGKILL)
            ref = compiled.execute(4)
            t0 = time.monotonic()
            with pytest.raises(Exception) as exc_info:
                ref.get(timeout=15)
            elapsed = time.monotonic() - t0
            assert elapsed < 15, "get() burned the whole timeout"
            assert not isinstance(exc_info.value, TimeoutError), \
                "dead stage surfaced as a bare timeout, not an error"
        finally:
            compiled.teardown(timeout=5)
    finally:
        ray_tpu.shutdown()


def test_stale_session_gc():
    """gc_stale_sessions removes session dirs whose registered pids are
    all dead, and leaves live sessions alone."""
    import tempfile

    from ray_tpu._private import lifecycle

    root = tempfile.mkdtemp(prefix="ray_tpu_gc_test_")
    try:
        # dead session: register a process that exits immediately
        dead = os.path.join(root, "session_dead")
        os.makedirs(dead)
        proc = subprocess.Popen([sys.executable, "-c", "pass"])
        proc.wait()
        lifecycle.register_process(dead, "agent", proc.pid)
        # live session: register ourselves via a child that stays alive
        live = os.path.join(root, "session_live")
        os.makedirs(live)
        sleeper = subprocess.Popen([sys.executable, "-c",
                                    "import time; time.sleep(60)"])
        lifecycle.register_process(live, "agent", sleeper.pid)
        try:
            removed = lifecycle.gc_stale_sessions([root])
            assert dead in removed
            assert not os.path.exists(dead)
            assert os.path.exists(live), "GC removed a LIVE session"
            # kill_live (stop --all) takes the live one too
            removed = lifecycle.gc_stale_sessions([root], kill_live=True)
            assert live in removed
            assert not os.path.exists(live)
            assert sleeper.poll() is not None or \
                _wait_pid_dead(sleeper, 5.0)
        finally:
            if sleeper.poll() is None:
                sleeper.kill()
    finally:
        import shutil

        shutil.rmtree(root, ignore_errors=True)


def _wait_pid_dead(proc, timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return True
        time.sleep(0.1)
    return False
