"""Cluster launcher — the `ray up` path (reference:
python/ray/autoscaler/_private/commands.py create_or_update_cluster +
command_runner.py SSHCommandRunner; VERDICT r2 missing #2).

A YAML/JSON cluster config names a head host and worker hosts; the
launcher drives each through a ``CommandRunner`` (SSH in production, a
local-process runner for tests — the reference's fake-multinode pattern):
run setup commands, start the head (`ray_tpu start --head`), then join
workers (`ray_tpu start --address`). ``down`` stops every node.

Config shape::

    cluster_name: demo
    provider:
      type: ssh            # or "local" (test runner)
      ssh_user: ubuntu
      ssh_private_key: ~/.ssh/key.pem
    head_node:
      host: 10.0.0.1
      port: 6379
      resources: {"CPU": 8}
    worker_nodes:
      - host: 10.0.0.2
        resources: {"CPU": 8, "TPU": 4}
    setup_commands:
      - echo ready
"""

from __future__ import annotations

import json
import shlex
import subprocess
import sys
from typing import Dict, List, Optional, Tuple


class CommandRunner:
    """Run shell commands on one node (reference: command_runner.py
    CommandRunnerInterface)."""

    def __init__(self, host: str):
        self.host = host

    def run(self, cmd: str, timeout: float = 300.0) -> Tuple[int, str]:
        raise NotImplementedError

    def check(self, cmd: str, timeout: float = 300.0) -> str:
        rc, out = self.run(cmd, timeout=timeout)
        if rc != 0:
            raise RuntimeError(
                f"[{self.host}] command failed (rc={rc}): {cmd}\n{out}")
        return out


class SSHCommandRunner(CommandRunner):
    """ssh/scp transport (reference: command_runner.py SSHCommandRunner —
    BatchMode, connection timeouts, IdentityFile)."""

    def __init__(self, host: str, user: Optional[str] = None,
                 private_key: Optional[str] = None,
                 ssh_options: Optional[List[str]] = None):
        super().__init__(host)
        self.user = user
        self.private_key = private_key
        self.ssh_options = list(ssh_options or [])

    def _base(self) -> List[str]:
        cmd = ["ssh", "-o", "BatchMode=yes", "-o", "ConnectTimeout=15",
               "-o", "StrictHostKeyChecking=accept-new"]
        if self.private_key:
            cmd += ["-i", self.private_key]
        cmd += self.ssh_options
        target = f"{self.user}@{self.host}" if self.user else self.host
        return cmd + [target]

    def run(self, cmd: str, timeout: float = 300.0) -> Tuple[int, str]:
        proc = subprocess.run(
            self._base() + [cmd], capture_output=True, text=True,
            timeout=timeout)
        return proc.returncode, proc.stdout + proc.stderr


class LocalCommandRunner(CommandRunner):
    """Run on THIS machine — the test/dev runner (reference: the
    fake-multinode provider's local exec path)."""

    def run(self, cmd: str, timeout: float = 300.0) -> Tuple[int, str]:
        proc = subprocess.run(
            ["bash", "-lc", cmd], capture_output=True, text=True,
            timeout=timeout)
        return proc.returncode, proc.stdout + proc.stderr


def load_cluster_config(path: str) -> Dict:
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return json.loads(text)
    import yaml

    return yaml.safe_load(text)


def validate_cluster_config(config: Dict) -> None:
    if not isinstance(config.get("head_node"), dict) \
            or "host" not in config["head_node"]:
        raise ValueError("cluster config needs head_node: {host: ...}")
    provider = config.get("provider") or {}
    if provider.get("type", "ssh") not in ("ssh", "local"):
        raise ValueError(
            f"unknown provider.type {provider.get('type')!r}; "
            "expected 'ssh' or 'local'")
    for w in config.get("worker_nodes") or []:
        if "host" not in w:
            raise ValueError(f"worker_nodes entry missing host: {w}")


class ClusterLauncher:
    def __init__(self, config: Dict,
                 runner_factory=None, python: str = sys.executable):
        validate_cluster_config(config)
        self.config = config
        self.python = python
        provider = config.get("provider") or {}
        if runner_factory is not None:
            self._make_runner = runner_factory
        elif provider.get("type", "ssh") == "local":
            self._make_runner = LocalCommandRunner
        else:
            self._make_runner = lambda host: SSHCommandRunner(
                host, user=provider.get("ssh_user"),
                private_key=provider.get("ssh_private_key"),
                ssh_options=provider.get("ssh_options"))

    # ------------------------------------------------------------- verbs
    def _start_cmd(self, node: Dict, head: bool, address: str = "") -> str:
        parts = [shlex.quote(self.python), "-m", "ray_tpu.scripts.cli",
                 "start"]
        if head:
            parts += ["--head", "--port",
                      str(self.config.get("head_node", {}).get("port", 0))]
        else:
            parts += ["--address", shlex.quote(address)]
        res = node.get("resources")
        if res:
            parts += ["--resources", shlex.quote(json.dumps(res))]
        return " ".join(parts)

    def up(self) -> str:
        """Setup + start head, then join workers; returns head address
        (reference: commands.py get_or_create_head_node + worker loop)."""
        head = self.config["head_node"]
        runner = self._make_runner(head["host"])
        for cmd in self.config.get("setup_commands") or []:
            runner.check(cmd)
        out = runner.check(self._start_cmd(head, head=True))
        address = self._parse_address(out, head)
        for worker in self.config.get("worker_nodes") or []:
            wrunner = self._make_runner(worker["host"])
            for cmd in self.config.get("setup_commands") or []:
                wrunner.check(cmd)
            wrunner.check(self._start_cmd(worker, head=False,
                                          address=address))
        return address

    def down(self) -> None:
        """Stop workers first, the head last (reference: teardown_cluster
        ordering)."""
        stop = (f"{shlex.quote(self.python)} -m ray_tpu.scripts.cli stop")
        for worker in self.config.get("worker_nodes") or []:
            try:
                self._make_runner(worker["host"]).check(stop)
            except Exception:
                pass  # best effort: a dead worker is already down
        self._make_runner(self.config["head_node"]["host"]).check(stop)

    @staticmethod
    def _parse_address(start_output: str, head: Dict) -> str:
        for line in start_output.splitlines():
            if line.startswith("head address:"):
                addr = line.split(":", 1)[1].strip()
                host, _, port = addr.partition(":")
                # the CLI reports the bind host as seen locally; remote
                # workers must dial the head's routable host
                return f"{head['host']}:{port}" \
                    if host in ("127.0.0.1", "0.0.0.0") \
                    and head["host"] not in ("127.0.0.1", "localhost") \
                    else addr
        raise RuntimeError(
            f"head start did not report an address:\n{start_output}")
