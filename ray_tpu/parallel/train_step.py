"""Sharded train/eval step builders (jit + GSPMD).

The reference's training step lives in user torch code wrapped by DDP
(reference: python/ray/train/torch/train_loop_utils.py prepare_model); here
the framework owns the step: loss → grad → optax update, jit-compiled with
explicit in/out shardings over the mesh so XLA emits psum/all_gather over
ICI. Gradient accumulation is a ``lax.scan`` over microbatches (static trip
count → one compiled body).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import (
    DEFAULT_RULES, LogicalAxisRules, logical_to_spec, param_shardings)


@dataclasses.dataclass
class TrainState:
    """Minimal functional train state (params live sharded on the mesh)."""
    step: jax.Array
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def create_train_state(
    init_fn: Callable[[jax.Array], Any],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    logical_axes: Any,
    *,
    rules: Optional[LogicalAxisRules] = None,
    seed: int = 0,
) -> Tuple[TrainState, Any]:
    """Initialize params directly sharded (jit with out_shardings so large
    models never materialize unsharded on one host)."""
    p_shardings = param_shardings(logical_axes, mesh, rules)
    key = jax.random.key(seed)

    init_jit = jax.jit(init_fn, out_shardings=p_shardings)
    params = init_jit(key)
    opt_shardings = _opt_state_shardings(tx, params, p_shardings, mesh)
    opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(params)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt_state)
    shardings = TrainState(
        step=NamedSharding(mesh, P()), params=p_shardings,
        opt_state=opt_shardings)
    return state, shardings


def _opt_state_shardings(tx, params, p_shardings, mesh):
    """Optimizer state shards like its matching param: any subtree of the
    state whose pytree structure equals the params' structure (adam mu/nu,
    momentum, …) gets the param shardings; everything else replicates."""
    shape_state = jax.eval_shape(tx.init, params)
    params_treedef = jax.tree.structure(params)
    repl = NamedSharding(mesh, P())

    def leaf_sharding(shape_leaf, sharding):
        # factored optimizers (adafactor v_row/v_col) mirror the params'
        # STRUCTURE with reduced-rank leaves; a param spec longer than the
        # leaf's rank is invalid, so replicate those
        spec_len = len([a for a in sharding.spec])
        if getattr(shape_leaf, "ndim", 0) < spec_len:
            return repl
        return sharding

    def assign(node):
        if jax.tree.structure(node) == params_treedef:
            return jax.tree.map(leaf_sharding, node, p_shardings)
        if isinstance(node, tuple):
            vals = [assign(c) for c in node]
            return type(node)(*vals) if hasattr(node, "_fields") \
                else tuple(vals)
        if isinstance(node, list):
            return [assign(c) for c in node]
        if isinstance(node, dict):
            return {k: assign(v) for k, v in node.items()}
        return jax.tree.map(lambda _: repl, node)

    return assign(shape_state)


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state_shardings: TrainState,
    *,
    rules: Optional[LogicalAxisRules] = None,
    batch_logical_axes: Tuple[Optional[str], ...] = ("batch", None),
    grad_accum: int = 1,
    donate: bool = True,
    frozen: Any = None,
    frozen_logical_axes: Any = None,
):
    """Returns jitted ``step(state, batch) -> (state, metrics)``.

    With grad_accum > 1, batch's leading dim is split into microbatches and
    scanned; grads average across the scan then update once.

    ``frozen`` (optional): a pytree of non-trainable parameters (a LoRA
    run's base model) passed to ``loss_fn(params, batch, frozen)``. It
    rides the jit as an ARGUMENT, never a closure — closing over it would
    capture the whole base model as lowered constants (13+ GB of HLO for
    a 7B base) and stall compilation. ``frozen_logical_axes`` shards it
    on the mesh (replicated when omitted).
    """
    rules = rules or DEFAULT_RULES
    batch_spec = logical_to_spec(batch_logical_axes, rules)
    batch_sharding = NamedSharding(mesh, batch_spec)

    def single_grad(params, batch, frozen_arg):
        if frozen is None:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, batch, frozen_arg)
        return loss, grads

    def step(state: TrainState, batch, frozen_arg
             ) -> Tuple[TrainState, Dict[str, Any]]:
        if grad_accum == 1:
            loss, grads = single_grad(state.params, batch, frozen_arg)
        else:
            micro = jax.tree.map(
                lambda x: x.reshape((grad_accum, x.shape[0] // grad_accum)
                                    + x.shape[1:]), batch)

            def body(carry, mb):
                loss_acc, gacc = carry
                loss, g = single_grad(state.params, mb, frozen_arg)
                return (loss_acc + loss,
                        jax.tree.map(jnp.add, gacc, g)), None

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (loss, grads), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zeros), micro)
            loss = loss / grad_accum
            grads = jax.tree.map(lambda g: g / grad_accum, grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               opt_state=new_opt)
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "step": new_state.step}

    metric_sharding = {"loss": NamedSharding(mesh, P()),
                       "grad_norm": NamedSharding(mesh, P()),
                       "step": NamedSharding(mesh, P())}
    if frozen is None:
        jitted = jax.jit(
            lambda state, batch: step(state, batch, None),
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, metric_sharding),
            donate_argnums=(0,) if donate else (),
        )
        return jitted
    frozen_shardings = (
        param_shardings(frozen_logical_axes, mesh, rules)
        if frozen_logical_axes is not None
        else jax.tree.map(
            # keep the base wherever its init placed it
            lambda x: getattr(x, "sharding", NamedSharding(mesh, P())),
            frozen))
    jitted = jax.jit(
        step,
        in_shardings=(state_shardings, batch_sharding, frozen_shardings),
        out_shardings=(state_shardings, metric_sharding),
        donate_argnums=(0,) if donate else (),
    )
    return lambda state, batch: jitted(state, batch, frozen)


def make_eval_step(
    loss_fn: Callable[[Any, Dict[str, jax.Array]], jax.Array],
    mesh: Mesh,
    state_shardings: TrainState,
    *,
    rules: Optional[LogicalAxisRules] = None,
    batch_logical_axes: Tuple[Optional[str], ...] = ("batch", None),
):
    rules = rules or DEFAULT_RULES
    batch_sharding = NamedSharding(
        mesh, logical_to_spec(batch_logical_axes, rules))

    def step(params, batch):
        return loss_fn(params, batch)

    return jax.jit(step, in_shardings=(state_shardings.params,
                                       batch_sharding),
                   out_shardings=NamedSharding(mesh, P()))
