"""ray:// client-mode tests (reference parity:
python/ray/tests/test_client.py — remote tasks, puts, actors, named actors,
errors over the client connection)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def client_ctx():
    from ray_tpu.util.client import serve

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    server = serve(host="127.0.0.1", port=0)
    ctx = ray_tpu.init(address=f"ray://127.0.0.1:{server.port}")
    yield ctx
    ctx.disconnect()
    server.stop()
    ray_tpu.shutdown()


def test_client_task(client_ctx):
    @client_ctx.remote
    def add(a, b):
        return a + b

    assert client_ctx.get(add.remote(2, 3), timeout=60) == 5


def test_client_put_get_roundtrip(client_ctx):
    arr = np.arange(10000, dtype=np.float32)
    ref = client_ctx.put(arr)
    out = client_ctx.get(ref, timeout=60)
    assert np.array_equal(out, arr)


def test_client_ref_as_task_arg(client_ctx):
    ref = client_ctx.put(21)

    @client_ctx.remote
    def double(x):
        return x * 2

    assert client_ctx.get(double.remote(ref), timeout=60) == 42


def test_client_task_error_propagates(client_ctx):
    @client_ctx.remote
    def boom():
        raise ValueError("client-visible error")

    with pytest.raises(Exception, match="client-visible error"):
        client_ctx.get(boom.remote(), timeout=60)


def test_client_actor(client_ctx):
    @client_ctx.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    assert client_ctx.get(c.inc.remote(), timeout=60) == 1
    assert client_ctx.get(c.inc.remote(5), timeout=60) == 6
    client_ctx.kill(c)


def test_client_named_actor(client_ctx):
    @client_ctx.remote
    class Store:
        def __init__(self):
            self.v = "named-ok"

        def read(self):
            return self.v

    Store.options(name="client_named", lifetime="detached").remote()
    h = client_ctx.get_actor("client_named")
    assert client_ctx.get(h.read.remote(), timeout=60) == "named-ok"


def test_client_wait(client_ctx):
    import time

    @client_ctx.remote
    def fast():
        return "f"

    @client_ctx.remote
    def slow():
        time.sleep(5)
        return "s"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = client_ctx.wait([f, s], num_returns=1, timeout=30)
    assert len(ready) == 1 and ready[0].hex() == f.hex()


def test_client_cluster_info(client_ctx):
    assert client_ctx.cluster_resources().get("CPU", 0) > 0
    assert any(n["alive"] for n in client_ctx.nodes())
