"""Optimizer + planner: logical chain → physical Topology.

Reference: python/ray/data/_internal/logical/optimizers.py (rule pipeline)
and _internal/planner/planner.py. Rules implemented:

- **Operator fusion** (rules/operator_fusion.py): consecutive task-compute
  map stages collapse into one task per block; a map stage directly above a
  Read fuses into the read task, so e.g. ``read_parquet(...).map_batches(f)``
  is one task per file.
- **Limit pushdown** (rules/limit_pushdown.py): Limit moves below pure
  per-row maps so slicing happens before the transform.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.data._internal import logical as L
from ray_tpu.data._internal.executor import Topology
from ray_tpu.data._internal import physical as P
from ray_tpu.data._internal import shuffle as S


# ------------------------------------------------------------- optimizer
def _fusable(op: L.LogicalOperator) -> bool:
    return isinstance(op, L.AbstractMap) and op.compute is None


def optimize(ops: List[L.LogicalOperator]) -> List[L.LogicalOperator]:
    ops = _limit_pushdown(ops)
    return _fuse(ops)


def _limit_pushdown(ops: List[L.LogicalOperator]) -> List[L.LogicalOperator]:
    out = list(ops)
    changed = True
    while changed:
        changed = False
        for i in range(1, len(out)):
            if (isinstance(out[i], L.Limit)
                    and isinstance(out[i - 1], L.AbstractMap)
                    and all(s.kind == "rows" for s in out[i - 1].specs)):
                out[i - 1], out[i] = out[i], out[i - 1]
                changed = True
    return out


def _fuse(ops: List[L.LogicalOperator]) -> List[L.LogicalOperator]:
    # Logical nodes are shared across derived Datasets (the chain is
    # immutable); fusion works on per-plan copies.
    import copy

    out: List[L.LogicalOperator] = []
    for op in ops:
        if (_fusable(op) and out and _fusable(out[-1])
                and (not out[-1].ray_remote_args or not op.ray_remote_args
                     or out[-1].ray_remote_args == op.ray_remote_args)):
            # refuse to fuse stages with conflicting resource requests
            # (reference: rules/operator_fusion.py _can_fuse)
            prev = out[-1]
            prev.specs = prev.specs + op.specs
            prev.name = f"{prev.name}->{op.name}"
            prev.ray_remote_args = op.ray_remote_args or prev.ray_remote_args
        elif (_fusable(op) and not op.ray_remote_args and out
              and isinstance(out[-1], L.Read)
              and not getattr(out[-1], "_no_fuse", False)):
            read = out[-1]
            read._fused_specs = getattr(read, "_fused_specs", []) + op.specs
            read.name = f"{read.name}->{op.name}"
        else:
            node = copy.copy(op)
            if isinstance(node, L.AbstractMap):
                node.specs = list(node.specs)
            out.append(node)
    return out


# --------------------------------------------------------------- planner
def plan(ops: List[L.LogicalOperator],
         max_concurrency: Optional[int] = None) -> Topology:
    if max_concurrency is None:
        from ray_tpu.data.context import DataContext

        max_concurrency = DataContext.get_current() \
            .max_tasks_in_flight_per_op
    topo = Topology()
    last = _plan_chain(ops, topo, max_concurrency)
    if last is None:
        raise ValueError("empty plan")
    return topo


def _plan_chain(ops: List[L.LogicalOperator], topo: Topology,
                max_concurrency: int) -> Optional[int]:
    last: Optional[int] = None
    for op in ops:
        if isinstance(op, L.Read):
            idx = topo.add(P.TaskPoolMapOperator(
                op.name, getattr(op, "_fused_specs", []),
                read_tasks=list(op.read_tasks),
                max_concurrency=max_concurrency))
        elif isinstance(op, L.InputData):
            idx = topo.add(P.InputDataBuffer(
                [P.RefBundle(ref, meta) for ref, meta in op.bundles]))
        elif isinstance(op, L.AbstractMap):
            compute = op.compute
            if compute is not None and getattr(compute, "is_actor_pool", False):
                spec = op.specs[0]
                idx = topo.add(P.ActorPoolMapOperator(
                    op.name, op.specs, spec.fn,
                    pool_size=compute.size,
                    fn_constructor_args=spec.fn_constructor_args,
                    fn_constructor_kwargs=spec.fn_constructor_kwargs,
                    ray_remote_args=op.ray_remote_args))
            else:
                idx = topo.add(P.TaskPoolMapOperator(
                    op.name, op.specs, max_concurrency=max_concurrency,
                    ray_remote_args=op.ray_remote_args))
        elif isinstance(op, L.Limit):
            idx = topo.add(P.LimitOperator(op.limit))
        elif isinstance(op, L.AbstractAllToAll):
            from ray_tpu.data.context import DataContext

            if (op.kind in ("random_shuffle", "sort")
                    and DataContext.get_current().streaming_shuffle):
                # pipelined per-shard exchange (ISSUE 12); the
                # materializing barrier below stays as the kill-switch
                # path and for the remaining bulk kinds
                from ray_tpu.data._internal.streaming_shuffle import (
                    build_streaming_shuffle)

                idx = topo.add(build_streaming_shuffle(op))
            else:
                idx = topo.add(P.AllToAllOperator(op.name, _bulk_fn(op)))
        elif isinstance(op, L.Union):
            idx = topo.add(P.UnionOperator(1 + len(op.others)))
            for branch in op.others:
                b_last = _plan_chain(
                    optimize(branch.chain()), topo, max_concurrency)
                topo.connect(b_last, idx)
        elif isinstance(op, L.Zip):
            idx = topo.add(P.ZipOperator())
            b_last = _plan_chain(
                optimize(op.other.chain()), topo, max_concurrency)
            topo.connect(b_last, idx, port="right")
        elif isinstance(op, L.Write):
            spec = L.MapSpec(kind="batches", fn=op.write_fn,
                             batch_format="default")
            idx = topo.add(P.TaskPoolMapOperator(
                op.name, [spec], max_concurrency=max_concurrency))
        else:
            raise TypeError(f"cannot plan {type(op).__name__}")
        if last is not None:
            topo.connect(last, idx)
        last = idx
    return last


def _bulk_fn(op: L.AbstractAllToAll):
    kw = op.kwargs
    if op.kind == "repartition":
        return S.repartition_fn(kw["num_blocks"])
    if op.kind == "random_shuffle":
        return S.random_shuffle_fn(kw.get("seed"), kw.get("num_blocks"))
    if op.kind == "sort":
        return S.sort_fn(kw["key"], kw.get("descending", False))
    if op.kind == "groupby_agg":
        return S.groupby_agg_fn(kw["key"], kw["aggs"],
                                kw.get("num_partitions"))
    if op.kind == "global_agg":
        return S.global_agg_fn(kw["aggs"])
    raise ValueError(f"unknown all-to-all kind {op.kind!r}")
