"""Median stopping rule (reference:
python/ray/tune/schedulers/median_stopping_rule.py — stop a trial whose
best score is worse than the median of running averages at the same time)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 grace_period: float = 1,
                 min_samples_required: int = 3,
                 hard_stop: bool = True):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples_required = min_samples_required
        self.hard_stop = hard_stop
        # trial_id -> list of (time, score)
        self._results: Dict[str, List] = {}

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        self._results.setdefault(trial.trial_id, []).append((t, score))
        if t < self.grace_period:
            return TrialScheduler.CONTINUE

        # running average of every *other* trial up to time t
        averages = []
        for tid, hist in self._results.items():
            if tid == trial.trial_id:
                continue
            pts = [s for (tt, s) in hist if tt <= t]
            if pts:
                averages.append(sum(pts) / len(pts))
        if len(averages) < self.min_samples_required:
            return TrialScheduler.CONTINUE
        averages.sort()
        median = averages[len(averages) // 2]
        best = max(s for (_, s) in self._results[trial.trial_id])
        if best < median:
            return (TrialScheduler.STOP if self.hard_stop
                    else TrialScheduler.PAUSE)
        return TrialScheduler.CONTINUE
