"""Partition-tolerant failure propagation (ISSUE 5).

The process killers in test_chaos.py exercise crash-class failures; this
file aims at the class TCP never reports — partitions, one-way links,
gray failures — using the frame-level fault plane in
``_private/protocol.py``. Recovery machinery under test: health-budget
death verdicts without an RST, incarnation fencing of partition
survivors, reconnect grace, fail-fast NodeDiedError/ActorDiedError
propagation, and the GCS background-loop supervisor.
"""

import asyncio
import os
import pickle
import time

import pytest

import ray_tpu
from ray_tpu._private import protocol as protocol_mod
from ray_tpu.exceptions import (
    ActorDiedError,
    DeathContext,
    NodeDiedError,
    OwnerDiedError,
    RayActorError,
)

# Tight failure-detection budget for every cluster in this file: the
# daemons inherit these at spawn. health budget = 0.5s * 4 = 2s.
CHAOS_ENV = {
    "RAY_TPU_FAULT_INJECTION": "1",
    "RAY_TPU_HEALTH_CHECK_PERIOD_MS": "500",
    "RAY_TPU_HEALTH_CHECK_FAILURE_THRESHOLD": "4",
    "RAY_TPU_NODE_DISCONNECT_GRACE_S": "2.0",
}
HEALTH_BUDGET_S = 2.0


@pytest.fixture
def chaos_env():
    saved = {k: os.environ.get(k) for k in CHAOS_ENV}
    os.environ.update(CHAOS_ENV)
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# unit: fault schedule + structured exceptions + loop supervisor
# ---------------------------------------------------------------------------


def test_fault_schedule_matching():
    sched = protocol_mod.FaultSchedule.from_json_dict({"rules": [
        {"self": "nodeA", "peer": "tcp", "direction": "out",
         "method": "*", "action": "drop"},
        {"self": "*", "peer": "tcp", "direction": "in",
         "method": "Echo", "action": "delay", "delay_s": 0.5},
    ]})
    protocol_mod.set_fault_self_id("nodeA")
    try:
        assert sched.match("out", "Anything", "tcp").action == "drop"
        # unix sockets (worker <-> local agent) are spared
        assert sched.match("out", "Anything", "unix") is None
        rule = sched.match("in", "Echo", "tcp")
        assert rule.action == "delay" and rule.delay_s == 0.5
        # replies (method None) only match blanket rules
        assert sched.match("in", None, "tcp") is None
    finally:
        protocol_mod.set_fault_self_id("")


def test_fault_injection_drops_and_delays_frames():
    async def main():
        server = protocol_mod.RpcServer("t")

        async def echo(conn, p):
            return p

        server.add_handler("Echo", echo)
        port = await server.start_tcp("127.0.0.1", 0)
        client = protocol_mod.AsyncRpcClient()
        await client.connect_tcp("127.0.0.1", port)
        assert await client.call("Echo", 1, timeout=5) == 1
        protocol_mod.set_fault_schedule(protocol_mod.FaultSchedule([
            protocol_mod.FaultRule(direction="out", method="Echo",
                                   action="drop")]))
        try:
            with pytest.raises(asyncio.TimeoutError):
                # the request frame is eaten; the socket stays open (no
                # ConnectionLost) — exactly a partition's signature
                await client.call("Echo", 2, timeout=0.4)
        finally:
            protocol_mod.set_fault_schedule(None)
        assert await client.call("Echo", 3, timeout=5) == 3
        protocol_mod.set_fault_schedule(protocol_mod.FaultSchedule([
            protocol_mod.FaultRule(direction="out", method="Echo",
                                   action="delay", delay_s=0.3)]))
        try:
            t0 = time.monotonic()
            assert await client.call("Echo", 4, timeout=5) == 4
            assert time.monotonic() - t0 >= 0.25
        finally:
            protocol_mod.set_fault_schedule(None)
        client.close()
        await server.close()

    asyncio.run(main())


def test_idle_monitor_fails_pending_calls_on_blackhole():
    async def main():
        server = protocol_mod.RpcServer("t")

        async def echo(conn, p):
            return p

        server.add_handler("Echo", echo)
        server.add_handler("Ping", echo)
        port = await server.start_tcp("127.0.0.1", 0)
        client = protocol_mod.AsyncRpcClient()
        await client.connect_tcp("127.0.0.1", port)
        client.start_idle_monitor(0.3)
        protocol_mod.set_fault_schedule(protocol_mod.FaultSchedule([
            protocol_mod.FaultRule(direction="both", method="*",
                                   action="drop")]))
        try:
            fut = client.call_future("Echo", 1)
            # the pending call would hang forever on the black-holed
            # socket; the idle monitor's unanswered ping kills the channel
            with pytest.raises(protocol_mod.ConnectionLost):
                await asyncio.wait_for(fut, timeout=10)
        finally:
            protocol_mod.set_fault_schedule(None)
        client.close()
        await server.close()

    asyncio.run(main())


def test_retry_call_bounded_with_jitter():
    async def main():
        calls = []

        async def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise protocol_mod.ConnectionLost("transient")
            return "ok"

        assert await protocol_mod.retry_call(
            lambda: flaky(), attempts=5, base_s=0.01, max_s=0.05) == "ok"
        assert len(calls) == 3

        async def doomed():
            raise protocol_mod.ConnectionLost("forever")

        with pytest.raises(protocol_mod.ConnectionLost):
            await protocol_mod.retry_call(
                lambda: doomed(), attempts=3, base_s=0.01, max_s=0.02)

        # application errors (the call ARRIVED) are not replayed
        async def app_error():
            calls.append("app")
            raise protocol_mod.RpcError("handler failed")

        calls.clear()
        with pytest.raises(protocol_mod.RpcError):
            await protocol_mod.retry_call(
                lambda: app_error(), attempts=4, base_s=0.01, max_s=0.02)
        assert calls == ["app"]

    asyncio.run(main())


def test_death_exceptions_roundtrip_serialization():
    """Satellite: NodeDiedError / ActorDiedError / OwnerDiedError carry
    structured context across the wire (the framework ships task errors
    pickled inside serialized values)."""
    from ray_tpu._private.serialization import SerializationContext

    ctx = SerializationContext()
    timeline = [(123.0, "node removed: partition"), (124.0, "call failed")]
    cases = [
        NodeDiedError(node_id="n" * 28, incarnation=77,
                      reason="health check timeout", timeline=timeline),
        ActorDiedError("actor1", "node died: partition",
                       node_id="n" * 28, incarnation=77, timeline=timeline),
        OwnerDiedError("obj1", node_id="n" * 28, incarnation=77,
                       reason="owner node fenced", timeline=timeline),
    ]
    for err in cases:
        for restored in (
                pickle.loads(pickle.dumps(err)),
                ctx.deserialize(memoryview(ctx.serialize(err).to_bytes()))):
            assert type(restored) is type(err)
            assert restored.context.node_id == "n" * 28
            assert restored.context.incarnation == 77
            assert restored.context.timeline == timeline
            assert restored.context.reason
    d = DeathContext.from_dict(cases[0].context.to_dict())
    assert d.timeline == timeline and d.incarnation == 77


def test_gcs_loop_supervisor_restarts_crashed_loops(tmp_path):
    from ray_tpu._private.gcs import HeadServer

    async def main():
        head = HeadServer(str(tmp_path))
        crashes = []

        async def crashy():
            crashes.append(1)
            if len(crashes) <= 2:
                raise RuntimeError("loop bug")
            # healthy from the third incarnation on

        task = asyncio.get_running_loop().create_task(
            head._supervise("crashy", crashy))
        await asyncio.wait_for(task, timeout=10)
        assert head.loop_restarts["crashy"] == 2
        assert len(crashes) == 3

    asyncio.run(main())


# ---------------------------------------------------------------------------
# end-to-end: one-way partition, fencing, fail-fast (acceptance criterion)
# ---------------------------------------------------------------------------


def test_one_way_partition_fences_node_and_fails_fast(chaos_env):
    """Under a one-way partition of a worker node (frames out are eaten,
    no RST ever): the head marks the node dead within the health budget,
    a driver blocked on an actor call to that node raises a death error
    (carrying node_id + incarnation) within ~2x the budget instead of
    hanging, and after the partition heals the fenced agent is rejected
    on re-register and exits — the lifecycle pid registry for that node
    converges to zero."""
    from ray_tpu._private import lifecycle
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.chaos import NetworkPartitioner

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    partitioner = None
    try:
        node = cluster.add_node(num_cpus=2, resources={"far": 4})
        ray_tpu.init(_node=cluster.head_node)
        cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"far": 0.01})
        class Victim:
            def ping(self):
                return "pong"

            def stall(self, seconds):
                time.sleep(seconds)
                return "done"

        victim = Victim.remote()
        assert ray_tpu.get(victim.ping.remote(), timeout=60) == "pong"
        pending = victim.stall.remote(300)  # in flight when the net cuts

        partitioner = NetworkPartitioner(cluster, mode="out")
        t0 = time.monotonic()
        partitioner.partition(node.node_id)

        # 1) death verdict within the health budget (+ scheduling slack
        # for a loaded 1-core CI box; the recorded latency is asserted,
        # not just the eventual outcome)
        detect_deadline = t0 + 4 * HEALTH_BUDGET_S + 10
        while time.monotonic() < detect_deadline:
            if not any(n["node_id"] == node.node_id and n["alive"]
                       for n in ray_tpu.nodes()):
                break
            time.sleep(0.05)
        detection_s = time.monotonic() - t0
        assert not any(n["node_id"] == node.node_id and n["alive"]
                       for n in ray_tpu.nodes()), \
            "head never marked the partitioned node dead (no RST arrived)"

        # 2) the blocked call fails fast with structured context instead
        # of waiting out the 300 s method / 600 s object deadline
        with pytest.raises((ActorDiedError, NodeDiedError,
                            RayActorError)) as exc_info:
            ray_tpu.get(pending, timeout=4 * HEALTH_BUDGET_S + 20)
        failfast_s = time.monotonic() - t0
        err = exc_info.value
        ctx = getattr(err, "context", None)
        if ctx is not None and ctx.node_id:
            assert ctx.node_id == node.node_id
        # a fresh call also fails immediately (DEAD state short-circuit)
        with pytest.raises((ActorDiedError, RayActorError)):
            ray_tpu.get(victim.ping.remote(), timeout=30)

        # 3) heal: the surviving agent re-registers, is fenced, and exits
        partitioner.heal(node.node_id)
        exit_deadline = time.monotonic() + 60
        while time.monotonic() < exit_deadline:
            if node.agent_proc.poll() is not None:
                break
            time.sleep(0.2)
        assert node.agent_proc.poll() is not None, \
            "fenced agent did not self-terminate after the partition healed"

        # 4) the node's pid registry converges to zero (fenced teardown
        # reaped its workers/forkserver too — no zombie lease holders)
        reg_deadline = time.monotonic() + 30
        while time.monotonic() < reg_deadline:
            if not lifecycle.live_registered(cluster.session_dir,
                                             node_id=node.node_id):
                break
            time.sleep(0.2)
        leftovers = lifecycle.live_registered(cluster.session_dir,
                                              node_id=node.node_id)
        assert not leftovers, f"zombie processes survived fencing: {leftovers}"
        # recorded latencies stay sane relative to the configured budget
        assert detection_s < 4 * HEALTH_BUDGET_S + 10
        assert failfast_s < detection_s + 4 * HEALTH_BUDGET_S + 20
    finally:
        if partitioner is not None:
            partitioner.heal()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_reconnect_grace_survives_tcp_blip(chaos_env):
    """A transient head<->agent TCP blip must NOT kill a healthy node's
    actors: the agent watchdog re-registers (same incarnation) inside the
    node_disconnect_grace_s window and the node is never marked dead."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        node = cluster.add_node(num_cpus=2, resources={"far": 4})
        ray_tpu.init(_node=cluster.head_node)
        cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"far": 0.01})
        class Sticky:
            def ping(self):
                return os.getpid()

        sticky = Sticky.remote()
        pid_before = ray_tpu.get(sticky.ping.remote(), timeout=60)

        # brief full partition, healed well inside detection: the head
        # sees heartbeats stop and (if the conn drops) a disconnect, but
        # the node returns before any verdict can land
        from ray_tpu.util.chaos import NetworkPartitioner

        partitioner = NetworkPartitioner(cluster, mode="both")
        partitioner.partition(node.node_id)
        time.sleep(HEALTH_BUDGET_S * 0.4)
        partitioner.heal(node.node_id)

        # the actor keeps its incarnation (same pid) and the node stays
        # alive through the blip
        deadline = time.monotonic() + 30
        pid_after = None
        while time.monotonic() < deadline:
            try:
                pid_after = ray_tpu.get(sticky.ping.remote(), timeout=10)
                break
            except Exception:
                time.sleep(0.2)
        assert pid_after == pid_before
        assert any(n["node_id"] == node.node_id and n["alive"]
                   for n in ray_tpu.nodes())
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# ---------------------------------------------------------------------------
# end-to-end: mixed chaos (satellite) — kills + partitions together
# ---------------------------------------------------------------------------


def test_workload_survives_node_kill_and_partition(chaos_env):
    """A task+actor workload with retries enabled runs to completion
    while BOTH failure planes fire: NodeKiller (crash-class, RST) and
    NetworkPartitioner (partition-class, no RST). Deterministic seeds,
    tight sizes (fast tier)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.chaos import NetworkPartitioner, NodeKiller

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    killer = part = None
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2)
        ray_tpu.init(_node=cluster.head_node)
        cluster.wait_for_nodes()

        @ray_tpu.remote(max_retries=8)
        def square(x):
            time.sleep(0.15)
            return x * x

        @ray_tpu.remote(max_restarts=8, max_task_retries=8)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                time.sleep(0.1)
                return self.n

        counter = Counter.remote()
        assert ray_tpu.get(counter.bump.remote(), timeout=60) == 1

        killer = NodeKiller(cluster, interval_s=1.5, max_kills=1,
                            seed=11).run()
        part = NetworkPartitioner(cluster, mode="both", duration_s=3.0,
                                  interval_s=2.0, max_kills=1, seed=12).run()
        try:
            refs = [square.remote(k) for k in range(16)]
            bumps = [ray_tpu.get(counter.bump.remote(), timeout=120)
                     for _ in range(6)]
            # hold the workload open until chaos has actually fired, so
            # this is a recovery test rather than a happy-path race
            fired_deadline = time.monotonic() + 60
            while time.monotonic() < fired_deadline and \
                    len(killer.kills) + len(part.kills) < 1:
                time.sleep(0.2)
            results = ray_tpu.get(refs, timeout=240)
            # post-chaos wave proves the cluster still schedules and the
            # restarted actor still answers
            assert ray_tpu.get(square.remote(5), timeout=120) == 25
            assert ray_tpu.get(counter.bump.remote(), timeout=120) >= 1
        finally:
            kills = killer.stop()
            partitions = part.stop()
        assert sorted(results) == [k * k for k in range(16)]
        assert all(b >= 1 for b in bumps)
        # chaos actually fired (deterministic seeds make this stable)
        assert len(kills) + len(partitions) >= 1, (kills, partitions)
    finally:
        if killer is not None:
            killer.stop()
        if part is not None:
            part.stop()
        ray_tpu.shutdown()
        cluster.shutdown()
