"""GKE TPU pod-slice node provider (reference:
python/ray/autoscaler/_private/gcp/node_provider.py + the kuberay
provider; SURVEY §7 phase 8 — slice-atomic scaling is the TPU-native
deviation: one v5e-16 slice is 4 hosts that must launch and die together,
because a single lost host invalidates the whole slice's ICI mesh).

The provider speaks a GKE-shaped node-pool API (`GkeNodePoolClient`); the
bundled `LocalMockGkeClient` "launches" each pool as local agent
processes, which is how the autoscaler tests exercise slice-atomic
scaling on one machine (reference test strategy: fake_multi_node).
Pointing the provider at a real client implementation is the production
path.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

# topology -> (hosts per slice, chips per host); v5e has 4 chips/host,
# v5p 4 chips/host with different host counts (reference:
# _private/accelerators/tpu.py pod-type tables)
TPU_TOPOLOGIES: Dict[str, tuple] = {
    "v5e-4": (1, 4),
    "v5e-8": (2, 4),
    "v5e-16": (4, 4),
    "v5e-32": (8, 4),
    "v5e-64": (16, 4),
    "v5e-128": (32, 4),
    "v5e-256": (64, 4),
    "v5p-8": (2, 4),
    "v5p-16": (4, 4),
    "v5p-32": (8, 4),
    "v4-8": (1, 4),
    "v4-16": (2, 4),
    "v4-32": (4, 4),
}


def slice_shape(topology: str) -> tuple:
    if topology not in TPU_TOPOLOGIES:
        raise ValueError(
            f"unknown TPU topology {topology!r}; known: "
            f"{sorted(TPU_TOPOLOGIES)}")
    return TPU_TOPOLOGIES[topology]


class GkeNodePoolClient:
    """The slice of GKE's node-pool API the provider needs. A production
    implementation wraps the container API; tests use LocalMockGkeClient."""

    def create_tpu_node_pool(self, pool_name: str, tpu_topology: str,
                             num_hosts: int, per_host_resources: Dict,
                             labels: Dict[str, str],
                             head_resources: Dict) -> None:
        raise NotImplementedError

    def delete_node_pool(self, pool_name: str) -> None:
        raise NotImplementedError

    def pool_runtime_node_ids(self, pool_name: str) -> List[str]:
        """Runtime node ids of the pool's hosts (empty until they boot)."""
        raise NotImplementedError


class LocalMockGkeClient(GkeNodePoolClient):
    """Boots each pool's hosts as real local agent processes joining the
    head — slice scheduling, registration, and teardown are exercised for
    real; only the cloud API is mocked."""

    def __init__(self, head_host: str, head_port: int, session_dir: str):
        self.head_host = head_host
        self.head_port = head_port
        self.session_dir = session_dir
        self._pools: Dict[str, List] = {}
        self._lock = threading.Lock()

    def create_tpu_node_pool(self, pool_name, tpu_topology, num_hosts,
                             per_host_resources, labels,
                             head_resources) -> None:
        from ray_tpu._private.node import Node

        hosts = []
        for i in range(num_hosts):
            resources = dict(per_host_resources)
            if i == 0:
                resources.update(head_resources)
            node = Node(
                head=False,
                head_host=self.head_host,
                head_port=self.head_port,
                resources=resources,
                labels={**labels, "tpu-worker-id": str(i)},
                session_dir=self.session_dir,
            )
            node.start()
            hosts.append(node)
        with self._lock:
            self._pools[pool_name] = hosts

    def delete_node_pool(self, pool_name: str) -> None:
        with self._lock:
            hosts = self._pools.pop(pool_name, [])
        for node in hosts:
            try:
                node.stop()
            except Exception:
                pass

    def pool_runtime_node_ids(self, pool_name: str) -> List[str]:
        with self._lock:
            hosts = list(self._pools.get(pool_name, []))
        return [nid for nid in (getattr(n, "node_id", None) for n in hosts)
                if nid]


class GkeTpuPodSliceProvider(NodeProvider):
    """Node provider whose unit of creation/termination for TPU types is a
    whole pod slice. ``node_types`` entries with a ``tpu_topology`` key are
    slice types; their ``resources`` (used by the demand packer and the
    synthetic boot-capacity absorber) are derived as the slice AGGREGATE.
    """

    def __init__(self, provider_config: Dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.node_types: Dict[str, Dict] = provider_config["node_types"]
        self.gke: GkeNodePoolClient = provider_config.get("gke_client") or \
            LocalMockGkeClient(provider_config["head_host"],
                               provider_config["head_port"],
                               provider_config["session_dir"])
        self._slices: Dict[str, Dict] = {}
        # RLock: provider state reads are reachable from GC context
        # (raylint R1) via the session pools' reap paths
        self._lock = threading.RLock()
        self._counter = 0
        for name, spec in self.node_types.items():
            topo = spec.get("tpu_topology")
            if not topo:
                continue
            hosts, chips = slice_shape(topo)
            cpus = float(spec.get("cpus_per_host", 1))
            spec.setdefault("resources", {
                "CPU": cpus * hosts, "TPU": float(chips * hosts)})
            spec["_per_host_resources"] = {"CPU": cpus, "TPU": float(chips)}
            spec["_hosts"] = hosts

    # ------------------------------------------------------------ lifecycle
    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._slices)

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._slices

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            info = self._slices.get(node_id)
        if not info:
            return {}
        return {"node_type": info["type"],
                "tpu-topology": info.get("topology", "")}

    # capacity-failure backoff windows (seconds): a quota/stockout retry
    # can succeed later; a 400/403 config error cannot fix itself — hold
    # off much longer and keep surfacing the event
    RETRYABLE_BACKOFF_S = 60.0
    PERMANENT_BACKOFF_S = 600.0

    def create_failure_backoff(self, node_type: str) -> float:
        """Seconds until this type may be retried (0 = clear)."""
        with self._lock:
            until = getattr(self, "_create_backoff", {}).get(node_type, 0.0)
        return max(0.0, until - time.time())

    def _note_create_failure(self, node_type: str, slice_id: str,
                             err: Exception) -> None:
        """Roll back bookkeeping for a slice the API refused and back off
        the type (VERDICT r3 weak #4: the quota/stockout/4xx path was
        speculative — now a failed create can't leave a ghost slice the
        autoscaler waits on forever, and can't hot-loop the API)."""
        from ray_tpu._private.event import report_event

        from ray_tpu.autoscaler.gke_rest import GkeApiError

        retryable = isinstance(err, GkeApiError) and err.retryable
        backoff = (self.RETRYABLE_BACKOFF_S if retryable
                   else self.PERMANENT_BACKOFF_S)
        with self._lock:
            self._slices.pop(slice_id, None)
            if not hasattr(self, "_create_backoff"):
                self._create_backoff = {}
            self._create_backoff[node_type] = time.time() + backoff
        # the operation may have half-created a degraded pool (stockout
        # mid-provision): deletion is idempotent, clean up best-effort
        try:
            self.gke.delete_node_pool(slice_id)
        except Exception:
            pass
        kind = "retryable" if retryable else "permanent"
        report_event(
            "WARNING" if retryable else "ERROR", "AUTOSCALER_CREATE_FAILED",
            f"GKE node-pool create failed for {node_type} ({kind}, "
            f"backing off {backoff:.0f}s): {err}",
            node_type=node_type, slice_id=slice_id)

    def create_node(self, node_type: str, count: int) -> List[str]:
        spec = self.node_types[node_type]
        topo = spec.get("tpu_topology")
        if not topo:
            raise ValueError(
                f"{type(self).__name__} only manages TPU slice types; "
                f"{node_type!r} has no tpu_topology")
        if self.create_failure_backoff(node_type) > 0:
            return []  # recent quota/stockout/config failure: hold off
        hosts, chips = slice_shape(topo)
        created = []
        for _ in range(count):
            with self._lock:
                self._counter += 1
                slice_id = f"{self.cluster_name}-{node_type}-{self._counter}"
                self._slices[slice_id] = {"type": node_type,
                                          "topology": topo,
                                          "created": time.time()}
            # pod-slice resource semantics (reference: tpu.py:335-398):
            # every host advertises {slice_name: 1}; host 0 additionally
            # advertises the slice-head resource a driver targets to fan
            # out one task per host
            per_host = dict(spec["_per_host_resources"])
            per_host[slice_id] = 1.0
            try:
                self.gke.create_tpu_node_pool(
                    pool_name=slice_id,
                    tpu_topology=topo,
                    num_hosts=hosts,
                    per_host_resources=per_host,
                    labels={"tpu-slice": slice_id, "tpu-topology": topo},
                    head_resources={f"TPU-{topo}-head": 1.0},
                )
            except Exception as e:
                # no ghost slices, no retry storms; callers get whatever
                # DID come up this round
                self._note_create_failure(node_type, slice_id, e)
                break
            created.append(slice_id)
        return created

    def terminate_node(self, node_id: str) -> None:
        """Slice-atomic: deleting the pool takes every host down with it."""
        with self._lock:
            info = self._slices.pop(node_id, None)
        if info:
            self.gke.delete_node_pool(node_id)

    def runtime_node_ids(self, node_id: str) -> List[str]:
        return self.gke.pool_runtime_node_ids(node_id)

    def runtime_node_id(self, node_id: str) -> Optional[str]:
        ids = self.runtime_node_ids(node_id)
        return ids[0] if ids else None

    def expected_runtime_nodes(self, node_id: str) -> int:
        with self._lock:
            info = self._slices.get(node_id)
        if not info:
            return 1
        return slice_shape(info["topology"])[0]

    def node_type_resources(self, node_type: str) -> Optional[Dict]:
        """Derived capacity for the autoscaler (aggregate + per-host), so
        it need not share this provider's mutable node_types dict."""
        spec = self.node_types.get(node_type)
        if not spec or "_per_host_resources" not in spec:
            return None
        return {"resources": dict(spec["resources"]),
                "per_host_resources": dict(spec["_per_host_resources"])}

    def num_slices(self) -> int:
        with self._lock:
            return len(self._slices)

    def shutdown(self) -> None:
        for nid in self.non_terminated_nodes():
            self.terminate_node(nid)
