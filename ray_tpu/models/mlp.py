"""Tiny MLP family — test/bench workhorse (the 'trivial task' of models)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: int = 512
    out_dim: int = 10
    num_layers: int = 2
    dtype: Any = jnp.float32


def mlp_logical_axes(cfg: MLPConfig) -> Dict[str, Any]:
    return {
        "w_in": ("embed", "mlp"),
        "b_in": ("mlp",),
        "w_hidden": (None, "mlp", "mlp"),
        "b_hidden": (None, "mlp"),
        "w_out": ("mlp", None),
        "b_out": (None,),
    }


def init_mlp(cfg: MLPConfig, key: jax.Array) -> Dict[str, Any]:
    ks = jax.random.split(key, 4)
    n_hid = max(cfg.num_layers - 2, 0)
    return {
        "w_in": jax.random.normal(ks[0], (cfg.in_dim, cfg.hidden),
                                  cfg.dtype) * cfg.in_dim ** -0.5,
        "b_in": jnp.zeros((cfg.hidden,), cfg.dtype),
        "w_hidden": jax.random.normal(
            ks[1], (n_hid, cfg.hidden, cfg.hidden),
            cfg.dtype) * cfg.hidden ** -0.5,
        "b_hidden": jnp.zeros((n_hid, cfg.hidden), cfg.dtype),
        "w_out": jax.random.normal(ks[2], (cfg.hidden, cfg.out_dim),
                                   cfg.dtype) * cfg.hidden ** -0.5,
        "b_out": jnp.zeros((cfg.out_dim,), cfg.dtype),
    }


def mlp_forward(params: Dict[str, Any], x: jax.Array,
                cfg: MLPConfig) -> jax.Array:
    h = jax.nn.relu(x @ params["w_in"] + params["b_in"])
    if params["w_hidden"].shape[0]:
        def body(h, wb):
            w, b = wb
            return jax.nn.relu(h @ w + b), None
        h, _ = jax.lax.scan(body, h, (params["w_hidden"],
                                      params["b_hidden"]))
    return h @ params["w_out"] + params["b_out"]


def mlp_loss(params, batch, cfg: MLPConfig) -> jax.Array:
    logits = mlp_forward(params, batch["x"], cfg)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(
        jnp.take_along_axis(logp, batch["y"][:, None], axis=-1))
