from ray_tpu._private.usage import usage_lib

__all__ = ["usage_lib"]
