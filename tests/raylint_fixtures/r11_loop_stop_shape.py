"""R11 regression fixture: loop-stop stranding an AsyncRpcClient read
loop (the BENCH tail leak, ISSUE 17 satellite).

The shipped bug: sync RPC facades over a private event-loop thread
(``util/client/client.py::_Channel``, ``autoscaler/monitor.py::
GcsChannel``) tore down with ``loop.call_soon_threadsafe(loop.stop)``
alone. ``AsyncRpcClient.close()`` only *cancels* the read-loop task;
the cancelled task needs one more loop tick, so stopping the loop first
strands it and the dying loop prints "Task was destroyed but it is
pending!" at interpreter teardown.

R11 must flag the two stop-without-aclose shapes below and must NOT
flag the aclose-first twin, the close_soon user, or loop stops in
classes that hold no AsyncRpcClient.
"""

import asyncio
import threading


class AsyncRpcClient:  # stand-in: the rule keys on the name
    async def aclose(self):
        pass

    def close_soon(self):
        pass


class ChannelBugShape:
    """The bug: stop the private loop, never await the read loop."""

    def __init__(self, host, port):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever)
        self.client = AsyncRpcClient()

    def close(self):
        self._loop.call_soon_threadsafe(self._loop.stop)  # expect-R11


class DirectStopBugShape:
    """Same bug, direct in-loop stop."""

    def __init__(self):
        self._loop = asyncio.new_event_loop()
        self.client = AsyncRpcClient()

    def shutdown(self):
        self._loop.stop()  # expect-R11


class ChannelFixedShape:
    """The fix: aclose ON the loop before stopping it."""

    def __init__(self):
        self._loop = asyncio.new_event_loop()
        self.client = AsyncRpcClient()

    def close(self):
        try:
            asyncio.run_coroutine_threadsafe(
                self.client.aclose(), self._loop).result(5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)


class CloseSoonShape:
    """Also fine: close_soon schedules the awaiting task for us."""

    def __init__(self):
        self._loop = asyncio.new_event_loop()
        self.client = AsyncRpcClient()

    def close(self):
        self.client.close_soon()
        self._loop.call_soon_threadsafe(self._loop.stop)


class NoClientShape:
    """No AsyncRpcClient held — stopping a loop is not itself a bug."""

    def __init__(self):
        self._loop = asyncio.new_event_loop()

    def close(self):
        self._loop.call_soon_threadsafe(self._loop.stop)
