"""ES — evolution strategies (reference: rllib/algorithms/es/es.py,
Salimans 2017: antithetic Gaussian perturbations evaluated by rollout
workers, centered-rank-weighted noise combination; no backprop at all).

The gradient-free outer loop fits the runtime naturally: each candidate
evaluation is one env-runner actor task; the combination step is a single
einsum on the (pop, dim) noise matrix.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.flatten_util
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Map fitnesses to centered uniform ranks in [-0.5, 0.5] (Salimans
    2017 fitness shaping — robust to return-scale outliers)."""
    ranks = np.empty(len(x), dtype=np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    return ranks / (len(x) - 1) - 0.5 if len(x) > 1 else np.zeros(1,
                                                                  np.float32)


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or ES)
        self.pop_size = 16          # perturbation PAIRS per iteration
        self.noise_stdev = 0.05
        self.step_size = 0.02       # SGD step on the combined gradient
        self.l2_coeff = 0.005
        self.episodes_per_candidate = 1
        self.rollout_fragment_length = 512  # >= one full episode
        self.num_env_runners = 4
        self.explore = False        # candidates run their mean policy

    def _training_keys(self):
        return {"pop_size", "noise_stdev", "step_size", "l2_coeff",
                "episodes_per_candidate"}


class ES(Algorithm):
    """No learner group: params live in the driver; env runners only
    evaluate (their sample() episode returns are the fitness signal)."""

    learner_cls = None

    @classmethod
    def get_default_config(cls):
        return ESConfig(algo_class=cls)

    def setup(self, _config) -> None:
        cfg = self.config = self._algo_config
        self._module_spec = cfg.module_spec()
        module = self._module_spec.build()
        params = module.init(jax.random.key(cfg.seed))
        flat, self._unravel = jax.flatten_util.ravel_pytree(params)
        self._theta = np.asarray(flat, np.float32)
        self._np_rng = np.random.default_rng(cfg.seed)
        self.env_runners: List = []
        for i in range(cfg.num_env_runners):
            self.env_runners.append(self._make_runner(i))
        self._total_env_steps = 0
        self._episode_returns: List[float] = []

    def get_weights(self):
        return jax.device_get(self._unravel(self._theta))

    def _fitness(self, sample: Dict) -> float:
        eps = sample["episodes"]
        if eps:
            return float(np.mean([e["episode_return"] for e in eps]))
        # no episode finished inside the fragment: fall back to the
        # fragment's summed reward so fitness stays informative
        return float(sample["rewards"].sum())

    def training_step(self) -> Dict:
        cfg = self.config
        dim = len(self._theta)
        noise = self._np_rng.standard_normal(
            (cfg.pop_size, dim)).astype(np.float32)

        candidates = np.concatenate([
            self._theta + cfg.noise_stdev * noise,
            self._theta - cfg.noise_stdev * noise])  # antithetic pairs
        refs = {}
        for i, cand in enumerate(candidates):
            runner = self.env_runners[i % len(self.env_runners)]
            w_ref = ray_tpu.put(jax.device_get(self._unravel(cand)))
            refs[runner.sample.remote(w_ref)] = i

        fitness = np.zeros(len(candidates), np.float32)
        steps_this_iter = 0
        for ref, i in refs.items():
            sample = ray_tpu.get(ref, timeout=600)
            fitness[i] = self._fitness(sample)
            steps_this_iter += sample["env_steps"]
            self._total_env_steps += sample["env_steps"]
            for ep in sample["episodes"]:
                self._episode_returns.append(ep["episode_return"])

        shaped = centered_ranks(fitness)
        pos, neg = shaped[:cfg.pop_size], shaped[cfg.pop_size:]
        grad = (pos - neg) @ noise / (2 * cfg.pop_size * cfg.noise_stdev)
        self._theta = ((1 - cfg.l2_coeff * cfg.step_size) * self._theta
                       + cfg.step_size * grad)

        return {
            "env_steps_this_iter": steps_this_iter,
            "fitness_mean": float(fitness.mean()),
            "fitness_max": float(fitness.max()),
            "theta_norm": float(np.linalg.norm(self._theta)),
        }

    def cleanup(self) -> None:
        for r in self.env_runners:
            try:
                ray_tpu.get(r.stop.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    # ----------------------------------------------------------- checkpoint
    def save_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "es_state.pkl"), "wb") as f:
            pickle.dump({"theta": self._theta,
                         "episode_returns": self._episode_returns,
                         "total_env_steps": self._total_env_steps}, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "es_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self._theta = state["theta"]
        self._episode_returns = state["episode_returns"]
        self._total_env_steps = state["total_env_steps"]

    def compute_single_action(self, obs, explore: bool = False):
        module = self._module_spec.build()
        out = module.forward(self.get_weights(), np.asarray(obs)[None])
        logits = np.asarray(out["logits"])[0]
        if module.spec.discrete:
            return int(np.argmax(logits))
        return np.tanh(logits[:module.spec.action_dim])
