"""ray_tpu.parallel — mesh formation, sharding rules, and parallel train steps.

This is the TPU-native replacement for the parallelism strategies the reference
reaches through integrations (DDP via torch process groups, FSDP/ZeRO via
DeepSpeed — reference: python/ray/train/torch/config.py:91,129,
python/ray/train/lightning/_lightning_utils.py:56-126). Here every strategy is
a mesh axis: data parallel = ``data``, ZeRO-3/FSDP = ``fsdp``, tensor parallel
= ``tensor``, sequence/context parallel = ``seq``, expert parallel =
``expert`` — and XLA GSPMD inserts the collectives over ICI/DCN.
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    create_mesh,
    best_mesh_shape,
    local_mesh,
)
from ray_tpu.parallel.sharding import (
    LogicalAxisRules,
    DEFAULT_RULES,
    logical_to_spec,
    shard_pytree,
    constrain,
    param_shardings,
)
from ray_tpu.parallel.train_step import (
    TrainState,
    create_train_state,
    make_train_step,
    make_eval_step,
)

__all__ = [
    "MeshConfig", "create_mesh", "best_mesh_shape", "local_mesh",
    "LogicalAxisRules", "DEFAULT_RULES", "logical_to_spec", "shard_pytree",
    "constrain", "param_shardings",
    "TrainState", "create_train_state", "make_train_step", "make_eval_step",
]
