"""R2D2 + external-env input tests (VERDICT r2 item 8).

- R2D2 learns a MEMORY task a feedforward policy cannot (the cue is only
  visible at t=0; reward-gated like tests/test_rllib_learning.py, the
  reference's learning-curve CI: rllib/tuned_examples/).
- An external PROCESS drives an env against a served policy via
  PolicyClient/PolicyServerInput (reference: rllib/env/policy_server_input.py,
  policy_client.py, external_env.py).
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest

import ray_tpu

try:
    import gymnasium as gym
except ImportError:  # pragma: no cover
    gym = None

pytestmark = pytest.mark.skipif(gym is None, reason="gymnasium required")


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class MemoryEnv(gym.Env if gym else object):
    """Cue ±1 shown ONLY at t=0; obs afterwards carries just a go-flag on
    the final step. Correct final action (0 for -1, 1 for +1) gives +1,
    wrong gives -1. A memoryless policy is blind at decision time (obs is
    identical for both cues) → expected return 0; recurrent state is the
    only path to the +1."""

    HORIZON = 3

    def __init__(self, config=None):
        self.observation_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._rng = np.random.default_rng(0)
        self._cue = 1
        self._t = 0

    def _obs(self):
        cue = float(self._cue) if self._t == 0 else 0.0
        go = 1.0 if self._t == self.HORIZON - 1 else 0.0
        return np.array([cue, go], np.float32)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._cue = 1 if self._rng.random() < 0.5 else -1
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        done = self._t == self.HORIZON - 1
        reward = 0.0
        if done:
            want = 1 if self._cue > 0 else 0
            reward = 1.0 if int(action) == want else -1.0
        self._t += 1
        return self._obs(), reward, done, False, {}


class TestR2D2:
    def test_module_recurrence_carries_information(self):
        """q_seq from stored state differs from zero state — the stored-
        state replay mechanic is live."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.algorithms.r2d2 import R2D2ModuleSpec

        spec = R2D2ModuleSpec(obs_dim=2, action_dim=2)
        m = spec.build()
        params = m.init(jax.random.key(0))
        obs_seq = jnp.zeros((4, 3, 2))
        zero_state = m.initial_state(3)
        warm_state = tuple(s + 0.7 for s in zero_state)
        q0, _ = m.q_seq(params, obs_seq, zero_state)
        q1, _ = m.q_seq(params, obs_seq, warm_state)
        assert not np.allclose(np.asarray(q0), np.asarray(q1))

    def test_h_rescale_inverse(self):
        from ray_tpu.rllib.algorithms.r2d2.r2d2 import (
            h_inverse, h_rescale)

        x = np.linspace(-50, 50, 101).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(h_inverse(h_rescale(x))), x, rtol=1e-3, atol=1e-3)

    def test_r2d2_learns_memory_task(self, ray4):
        from ray_tpu.rllib import R2D2Config

        config = (R2D2Config()
                  .environment(env=MemoryEnv)
                  .env_runners(num_env_runners=1,
                               num_envs_per_env_runner=8,
                               rollout_fragment_length=12)
                  .training(lr=1e-3, train_batch_size=16, gamma=0.97,
                            burn_in=3))
        config.epsilon = [(0, 1.0), (3000, 0.05)]
        config.target_network_update_freq = 200
        config.num_steps_sampled_before_learning_starts = 200
        algo = config.build()
        try:
            best = -np.inf
            for _ in range(60):
                result = algo.train()
                value = result.get("episode_return_mean")
                if value is not None and np.isfinite(value):
                    best = max(best, value)
                if best >= 0.5:
                    break
            # memoryless ceiling is 0.0; only recurrence clears 0.5
            assert best >= 0.5, best
        finally:
            algo.stop()


class TestPolicyServer:
    def _module_spec(self):
        from ray_tpu.rllib.core.rl_module import RLModuleSpec

        return RLModuleSpec(obs_dim=2, action_dim=2, discrete=True)

    def test_external_process_drives_env(self):
        import jax

        from ray_tpu.rllib.env.policy_server_input import PolicyServerInput

        spec = self._module_spec()
        server = PolicyServerInput(spec, seed=0)
        try:
            weights = spec.build().init(jax.random.key(0))
            server.set_weights(weights)
            # the EXTERNAL side: a separate python process owning the env
            # loop, talking only HTTP via PolicyClient
            script = textwrap.dedent(f"""
                import numpy as np
                from ray_tpu.rllib.env.policy_client import PolicyClient

                client = PolicyClient("{server.address}")
                for ep in range(3):
                    eid = client.start_episode()
                    obs = np.array([1.0, 0.0], np.float32)
                    for t in range(4):
                        action = client.get_action(eid, obs)
                        assert action in (0, 1), action
                        client.log_returns(eid, 0.25)
                        obs = np.array([0.0, float(t == 2)], np.float32)
                    client.end_episode(eid, obs)
                print("CLIENT_OK")
            """)
            proc = subprocess.run(
                [sys.executable, "-c", script], capture_output=True,
                text=True, timeout=120)
            assert proc.returncode == 0, proc.stderr
            assert "CLIENT_OK" in proc.stdout
            batch = server.sample(weights, min_transitions=12, timeout=10)
            assert batch["env_steps"] == 12       # 3 eps x 4 transitions
            assert batch["obs"].shape == (1, 12, 2)
            assert batch["next_obs"].shape == (1, 12, 2)
            # one terminal per episode; rewards attribute to their action
            assert batch["dones"].sum() == 3
            np.testing.assert_allclose(batch["rewards"],
                                       np.full((1, 12), 0.25))
            assert len(batch["episodes"]) == 3
            assert batch["episodes"][0]["episode_return"] == \
                pytest.approx(1.0)
        finally:
            server.stop()

    def test_server_feeds_dqn_learner(self):
        """Transitions from external clients train a DQN learner with no
        adapter — the off-policy batch layouts match."""
        import jax
        import threading

        from ray_tpu.rllib.algorithms.dqn.dqn import (
            DQNLearner, DQNModuleSpec)
        from ray_tpu.rllib.env.policy_client import PolicyClient
        from ray_tpu.rllib.env.policy_server_input import PolicyServerInput

        spec = DQNModuleSpec(obs_dim=2, action_dim=2)
        server = PolicyServerInput(spec, seed=1)
        learner = DQNLearner(spec, {"lr": 1e-3, "seed": 0},
                             use_mesh=False)
        try:
            server.set_weights(learner.get_weights())

            def drive():
                client = PolicyClient(server.address)
                rng = np.random.default_rng(0)
                for _ in range(4):
                    eid = client.start_episode()
                    obs = rng.normal(size=2).astype(np.float32)
                    for t in range(5):
                        client.get_action(eid, obs)
                        client.log_returns(eid, float(rng.random()))
                        obs = rng.normal(size=2).astype(np.float32)
                    client.end_episode(eid, obs)

            t = threading.Thread(target=drive)
            t.start()
            batch = server.sample(learner.get_weights(),
                                  min_transitions=20, timeout=60)
            t.join(timeout=30)
            flat = lambda a: np.asarray(a).reshape(
                (-1,) + np.asarray(a).shape[2:])
            out = learner.update({
                "obs": flat(batch["obs"]),
                "actions": flat(batch["actions"]).astype(np.int64),
                "rewards": flat(batch["rewards"]),
                "next_obs": flat(batch["next_obs"]),
                "dones": flat(batch["dones"]),
            })
            assert np.isfinite(out["total_loss"])
        finally:
            server.stop()


def test_piecewise_schedule_honors_midpoints():
    """Shared epsilon schedule (rllib/utils/schedules.py): adjacent-pair
    interpolation — a 3-point schedule's fast initial decay is honored
    instead of one flat first-to-last ramp."""
    from ray_tpu.rllib.utils.schedules import piecewise_linear

    sched = [(0, 1.0), (1000, 0.1), (10000, 0.05)]
    assert piecewise_linear(sched, 0) == 1.0
    assert abs(piecewise_linear(sched, 500) - 0.55) < 1e-9   # fast leg
    assert abs(piecewise_linear(sched, 1000) - 0.1) < 1e-9
    assert abs(piecewise_linear(sched, 5500) - 0.075) < 1e-9  # slow leg
    assert piecewise_linear(sched, 99999) == 0.05
    assert piecewise_linear([(0, 0.3)], 12345) == 0.3
