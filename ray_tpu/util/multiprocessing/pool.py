"""Pool implementation (reference: python/ray/util/multiprocessing/pool.py:
Pool's map/imap/apply family executed by a pool of actors so chunks inherit
the cluster's scheduling + fault tolerance instead of local forks)."""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class _PoolWorker:
    """One actor per pool slot; runs chunks of work."""

    def run_chunk(self, fn, chunk: List, is_starmap: bool, kwargs=None):
        if is_starmap:
            return [fn(*args, **(kwargs or {})) for args in chunk]
        return [fn(item, **(kwargs or {})) for item in chunk]


class AsyncResult:
    def __init__(self, refs: List, single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        parts = ray_tpu.get(self._refs, timeout=timeout)
        flat = [x for part in parts for x in part]
        return flat[0] if self._single else flat

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """API-compatible subset of multiprocessing.Pool: apply, apply_async,
    map, map_async, starmap, imap, imap_unordered, close/terminate/join."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(int(ray_tpu.cluster_resources().get("CPU", 1)), 1)
        self._size = processes
        args = ray_remote_args or {"resources": {"CPU": 1}}
        worker_cls = ray_tpu.remote(_PoolWorker)
        if initializer is not None:
            init = initializer  # run per-actor before first chunk

            class _InitWorker(_PoolWorker):
                def __init__(self):
                    init(*initargs)

            worker_cls = ray_tpu.remote(_InitWorker)
        self._workers = [worker_cls.options(**args).remote()
                         for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        self._lock = threading.Lock()

    def _next_worker(self):
        with self._lock:
            return self._workers[next(self._rr)]

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    # ----------------------------------------------------------------- api
    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict]
              = None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check_open()
        ref = self._next_worker().run_chunk.remote(fn, [args], True, kwds)
        return AsyncResult([ref], single=True)

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._size * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        refs = [self._next_worker().run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks(iterable, chunksize)]
        return AsyncResult(refs)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List:
        self._check_open()
        refs = [self._next_worker().run_chunk.remote(fn, chunk, True)
                for chunk in self._chunks(iterable, chunksize)]
        return AsyncResult(refs).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        self._check_open()
        refs = [self._next_worker().run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks(iterable, chunksize)]
        for ref in refs:  # submission order
            for item in ray_tpu.get(ref):
                yield item

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check_open()
        refs = [self._next_worker().run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks(iterable, chunksize)]
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for item in ray_tpu.get(ready[0]):
                yield item

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        # raylint: disable=R13 -- monotonic GIL-atomic bool flip (False
        # ->True only, mirroring close()); racy readers at worst submit
        # to a closing pool, which terminate's kill loop handles anyway
        self._closed = True
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
