"""Native (C++) kernel components, bound via ctypes.

The reference implements its node-local kernel in C++ (reference:
``src/ray/object_manager/plasma/store.h:55`` — the shared-memory object store
that lives inside the raylet). This package holds the TPU-native C++
equivalents: ``store.cc`` (shm arena object store) compiled on first use into
``~/.cache/ray_tpu/`` and loaded with ctypes (no pybind11 in this image).

``NativeStoreClient`` mirrors the Python ``StoreClient`` API
(ray_tpu/_private/object_store.py) so the worker runtime can switch backends
transparently; set ``RAY_TPU_NATIVE_STORE=0`` to force the pure-Python tmpfs
backend.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional, Tuple

_build_lock = threading.Lock()
_lib = None
_lib_failed = False

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["store.cc", "sched.cc"]


def _cache_dir() -> str:
    d = os.environ.get("RAY_TPU_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "ray_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _source_digest() -> str:
    h = hashlib.sha256()
    for src in _SOURCES:
        with open(os.path.join(_SRC_DIR, src), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def build_native_lib() -> Optional[str]:
    """Compile the native kernel to a cached .so; returns its path or None."""
    out = os.path.join(_cache_dir(), f"libray_tpu_{_source_digest()}.so")
    if os.path.exists(out):
        return out
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    tmp = out + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
           *srcs, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.rename(tmp, out)  # atomic publish; racing builders both succeed
        return out
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def get_native_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native kernel library."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    with _build_lock:
        if _lib is not None:
            return _lib
        if os.environ.get("RAY_TPU_NATIVE_STORE", "1") == "0":
            _lib_failed = True
            return None
        path = build_native_lib()
        if path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _lib_failed = True
            return None
        lib.tpu_store_create.restype = ctypes.c_void_p
        lib.tpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.tpu_store_attach.restype = ctypes.c_void_p
        lib.tpu_store_attach.argtypes = [ctypes.c_char_p]
        lib.tpu_store_detach.argtypes = [ctypes.c_void_p]
        lib.tpu_store_base.restype = ctypes.POINTER(ctypes.c_ubyte)
        lib.tpu_store_base.argtypes = [ctypes.c_void_p]
        lib.tpu_store_create_object.restype = ctypes.c_uint64
        lib.tpu_store_create_object.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        for fn in ("tpu_store_seal", "tpu_store_abort", "tpu_store_contains",
                   "tpu_store_release", "tpu_store_delete"):
            f = getattr(lib, fn)
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tpu_store_get.restype = ctypes.c_int
        lib.tpu_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        lib.tpu_store_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        # test-only robust-mutex hook (store.cc tpu_store_test_lock_and_leak)
        lib.tpu_store_test_lock_and_leak.restype = ctypes.c_int
        lib.tpu_store_test_lock_and_leak.argtypes = [ctypes.c_void_p]
        lib.tpu_store_lru_candidates.restype = ctypes.c_int
        lib.tpu_store_lru_candidates.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int]
        dbl = ctypes.POINTER(ctypes.c_double)
        i32 = ctypes.POINTER(ctypes.c_int)
        lib.tpu_sched_best_node.restype = ctypes.c_int
        lib.tpu_sched_best_node.argtypes = [
            dbl, dbl, ctypes.c_int, ctypes.c_int, dbl, ctypes.c_double]
        lib.tpu_sched_first_feasible.restype = ctypes.c_int
        lib.tpu_sched_first_feasible.argtypes = [
            dbl, ctypes.c_int, ctypes.c_int, dbl]
        lib.tpu_sched_bin_pack.restype = ctypes.c_int
        lib.tpu_sched_bin_pack.argtypes = [
            dbl, ctypes.c_int, dbl, ctypes.c_int, dbl, ctypes.c_int,
            i32, i32, ctypes.c_int, i32,
            ctypes.POINTER(ctypes.c_uint8)]
        _lib = lib
        return _lib


class NativeStore:
    """Handle to one shm arena segment (create or attach)."""

    def __init__(self, path: str, capacity: Optional[int] = None,
                 create: bool = False):
        lib = get_native_lib()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self._lib = lib
        self.path = path
        if create:
            self._h = lib.tpu_store_create(path.encode(), capacity or 0)
            if not self._h:
                # lost a creation race — attach instead
                self._h = lib.tpu_store_attach(path.encode())
        else:
            self._h = lib.tpu_store_attach(path.encode())
        if not self._h:
            raise RuntimeError(f"cannot open native store at {path}")
        # one flat view over the whole mapping; object views slice into it
        import ctypes as ct

        base = lib.tpu_store_base(self._h)
        stats = self.stats()
        seg_size = self._segment_size()
        self._buf = (ct.c_ubyte * seg_size).from_address(
            ct.addressof(base.contents))
        self._view = memoryview(self._buf).cast("B")
        del stats

    def _segment_size(self) -> int:
        return os.path.getsize(self.path)

    # -- object lifecycle --------------------------------------------------
    def create(self, id_bytes: bytes, size: int) -> Optional[memoryview]:
        off = self._lib.tpu_store_create_object(self._h, id_bytes, size)
        if off == 0:
            return None
        return self._view[off:off + max(size, 1)]

    def seal(self, id_bytes: bytes) -> bool:
        return self._lib.tpu_store_seal(self._h, id_bytes) == 0

    def abort(self, id_bytes: bytes) -> bool:
        return self._lib.tpu_store_abort(self._h, id_bytes) == 0

    def get(self, id_bytes: bytes) -> Optional[memoryview]:
        """Zero-copy view of a sealed object (pins it; call release after)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.tpu_store_get(
            self._h, id_bytes, ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return self._view[off.value:off.value + max(size.value, 1)]

    def get_pinned_view(self, id_bytes: bytes) -> Optional[memoryview]:
        """Zero-copy view whose pin is released automatically when the last
        Python alias of the buffer is garbage-collected — safe to hand to
        deserializers that keep numpy/jax arrays aliasing the store."""
        import weakref

        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.tpu_store_get(
            self._h, id_bytes, ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        n = max(size.value, 1)
        arr = (ctypes.c_ubyte * n).from_address(
            ctypes.addressof(self._buf) + off.value)
        weakref.finalize(arr, self._lib.tpu_store_release, self._h, id_bytes)
        arr._keepalive = self  # segment mapping must outlive the view
        return memoryview(arr).cast("B")

    def contains(self, id_bytes: bytes) -> bool:
        return self._lib.tpu_store_contains(self._h, id_bytes) == 1

    def release(self, id_bytes: bytes) -> None:
        self._lib.tpu_store_release(self._h, id_bytes)

    def delete(self, id_bytes: bytes) -> bool:
        return self._lib.tpu_store_delete(self._h, id_bytes) == 0

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 6)()
        self._lib.tpu_store_stats(self._h, out)
        return {
            "used": out[0], "capacity": out[1], "num_objects": out[2],
            "num_evictions": out[3], "num_created": out[4],
        }

    def lru_candidates(self, max_n: int = 16) -> list:
        buf = (ctypes.c_ubyte * (20 * max_n))()
        n = self._lib.tpu_store_lru_candidates(self._h, buf, max_n)
        raw = bytes(buf)
        return [raw[i * 20:(i + 1) * 20] for i in range(n)]

    def close(self) -> None:
        if self._h:
            # detach munmaps: only call at process shutdown, after all views
            # into the segment are dead. The segment file itself persists.
            self._lib.tpu_store_detach(self._h)
            self._h = None


class NativeScheduler:
    """Dense-vector scheduling kernel over an interned resource-name space
    (sched.cc — the cluster_resource_data / hybrid policy / bin-packing
    analogs). Callers intern resource names to column indices once and ship
    flat float64 matrices."""

    def __init__(self):
        lib = get_native_lib()
        if lib is None:
            raise RuntimeError("native scheduler library unavailable")
        self._lib = lib
        self._names: dict = {}

    def intern(self, name: str) -> int:
        if name not in self._names:
            self._names[name] = len(self._names)
        return self._names[name]

    @property
    def n_res(self) -> int:
        return len(self._names)

    def to_vec(self, resources: dict, n_res: Optional[int] = None):
        import numpy as np

        for name in resources:
            self.intern(name)
        vec = np.zeros(n_res or self.n_res, np.float64)
        for name, qty in resources.items():
            idx = self._names[name]
            if idx < len(vec):
                vec[idx] = float(qty)
        return vec

    def best_node(self, avail_rows, total_rows, request,
                  spread_threshold: float = 0.8) -> int:
        """avail/total: list of resource dicts; returns node index or -1."""
        import numpy as np

        for d in (*avail_rows, *total_rows, request):
            for name in d:
                self.intern(name)
        n = self.n_res
        avail = np.ascontiguousarray(
            [self.to_vec(d, n) for d in avail_rows], np.float64)
        total = np.ascontiguousarray(
            [self.to_vec(d, n) for d in total_rows], np.float64)
        req = self.to_vec(request, n)
        dblp = ctypes.POINTER(ctypes.c_double)
        return self._lib.tpu_sched_best_node(
            avail.ctypes.data_as(dblp), total.ctypes.data_as(dblp),
            len(avail_rows), n, req.ctypes.data_as(dblp),
            ctypes.c_double(spread_threshold))

    def bin_pack(self, demands, pools, node_types, max_workers: int,
                 total_workers: int, existing_counts: dict) -> dict:
        """Autoscaler packing (mirrors resource_demand_scheduler semantics).

        demands/pools: lists of resource dicts; node_types:
        {name: {"resources": dict, "max_workers": int}}. Returns
        {type: count} to launch.
        """
        import numpy as np

        type_names = list(node_types)
        for d in (*demands, *pools,
                  *(node_types[t].get("resources", {}) for t in type_names)):
            for name in d:
                self.intern(name)
        n = self.n_res
        if not demands:
            return {}
        dm = np.ascontiguousarray(
            [self.to_vec(d, n) for d in demands], np.float64)
        pl = (np.ascontiguousarray([self.to_vec(p, n) for p in pools],
                                   np.float64)
              if pools else np.zeros((0, n), np.float64))
        caps = np.ascontiguousarray(
            [self.to_vec(node_types[t].get("resources", {}), n)
             for t in type_names], np.float64)
        max_new = np.ascontiguousarray(
            [max(0, node_types[t].get("max_workers", max_workers)
                 - existing_counts.get(t, 0)) for t in type_names],
            np.int32)
        budget = np.array([max(0, max_workers - total_workers)], np.int32)
        out_launch = np.zeros(len(type_names), np.int32)
        unfulfilled = np.zeros(len(demands), np.uint8)
        dblp = ctypes.POINTER(ctypes.c_double)
        i32p = ctypes.POINTER(ctypes.c_int)
        self._lib.tpu_sched_bin_pack(
            dm.ctypes.data_as(dblp), len(demands),
            pl.ctypes.data_as(dblp), len(pools),
            caps.ctypes.data_as(dblp), len(type_names),
            max_new.ctypes.data_as(i32p), budget.ctypes.data_as(i32p), n,
            out_launch.ctypes.data_as(i32p),
            unfulfilled.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        return {t: int(c) for t, c in zip(type_names, out_launch) if c > 0}
