"""QMIX learning + model catalog (reference: the QMIX family and
rllib/models/ catalog; VERDICT r1 item 4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv


class CoordGame(MultiAgentEnv):
    """Two agents, 3 actions, one step: both picking action 2 pays +10
    total; both picking 0 pays +4 (safe); any mismatch pays 0. Greedy
    independent learners get stuck on the safe action; QMIX's joint value
    factorization finds the coordinated optimum."""

    possible_agents = ["a0", "a1"]

    def __init__(self, config=None):
        import gymnasium as gym

        self._obs_space = gym.spaces.Box(0.0, 1.0, (2,), np.float32)
        self._act_space = gym.spaces.Discrete(3)

    @property
    def observation_spaces(self):
        return {a: self._obs_space for a in self.possible_agents}

    @property
    def action_spaces(self):
        return {a: self._act_space for a in self.possible_agents}

    def reset(self, *, seed=None):
        obs = np.asarray([1.0, 0.0], np.float32)
        return {a: obs.copy() for a in self.possible_agents}, {}

    def step(self, action_dict):
        a0, a1 = action_dict["a0"], action_dict["a1"]
        if a0 == 2 and a1 == 2:
            team = 10.0
        elif a0 == 0 and a1 == 0:
            team = 4.0
        else:
            team = 0.0
        obs = {a: np.asarray([0.0, 1.0], np.float32)
               for a in self.possible_agents}
        rewards = {a: team / 2 for a in self.possible_agents}
        dones = {"__all__": True, "a0": True, "a1": True}
        truncs = {"__all__": False}
        return obs, rewards, dones, truncs, {}


def test_qmix_learns_coordination():
    from ray_tpu.rllib import QMIXConfig

    cfg = (QMIXConfig()
           .environment(CoordGame)
           .training(lr=2e-3, train_batch_size=64,
                     target_network_update_freq=200,
                     num_env_steps_per_iter=64)
           .debugging(seed=3))
    cfg.epsilon = [(0, 1.0), (2500, 0.05)]
    cfg.num_steps_sampled_before_learning_starts = 128
    algo = cfg.build()
    best = -np.inf
    for i in range(90):
        r = algo.train()
        ret = r.get("episode_return_mean")
        if ret is not None:
            best = max(best, ret)
        if best >= 8.0:
            break
    algo.stop()
    # the safe equilibrium pays 4; >=8 requires coordinated action 2
    assert best >= 8.0, f"QMIX failed to coordinate: best={best}"


def test_qmix_mixer_is_monotonic():
    from ray_tpu.rllib.algorithms.qmix.qmix import QMixModel

    model = QMixModel(obs_dim=4, state_dim=8, n_agents=2, n_actions=3)
    params = model.init(jax.random.key(0))
    state = jnp.ones((1, 8))
    q = jnp.asarray([[0.3, -0.2]])
    base = model.mix(params, q, state)[0]
    # raising any agent's Q must not lower Q_tot (monotonic mixing)
    for i in range(2):
        bumped = q.at[0, i].add(0.5)
        assert model.mix(params, bumped, state)[0] >= base - 1e-5


# --------------------------------------------------------- model catalog
def test_conv_module_shapes_and_grads():
    from ray_tpu.rllib.core.rl_module import RLModuleSpec

    spec = RLModuleSpec(obs_dim=0, action_dim=5, obs_shape=(24, 24, 3))
    mod = spec.build()
    assert type(mod).__name__ == "ConvModule"
    params = mod.init(jax.random.key(0))
    obs = jnp.ones((6, 24, 24, 3))
    out = mod.forward(params, obs)
    assert out["logits"].shape == (6, 5) and out["vf"].shape == (6,)
    grads = jax.grad(lambda p: mod.forward(p, obs)["logits"].sum())(params)
    assert all(bool(jnp.isfinite(g).all())
               for g in jax.tree.leaves(grads))
    action, logp, vf = mod.explore_action(params, obs, jax.random.key(1))
    assert action.shape == (6,) and logp.shape == (6,)
    # single-observation (unbatched) path
    single = mod.forward(params, jnp.ones((24, 24, 3)))
    assert single["logits"].shape == (5,)


def test_conv_module_can_fit_labels():
    """A tiny supervised fit proves gradients move the conv tower."""
    import optax

    from ray_tpu.rllib.core.rl_module import RLModuleSpec

    spec = RLModuleSpec(obs_dim=0, action_dim=2, obs_shape=(10, 10, 1),
                        conv_filters=((8, 3, 2), (16, 3, 2)))
    mod = spec.build()
    params = mod.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 10, 10, 1)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, X, y):
        def loss(p):
            logits = mod.forward(p, X)["logits"]
            logps = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(
                logps, y[:, None], axis=1))

        l, g = jax.value_and_grad(loss)(params)
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt, l

    first = None
    for i in range(120):
        params, opt, l = step(params, opt, X, y)
        if first is None:
            first = float(l)
    assert float(l) < first * 0.5, (first, float(l))


def test_lstm_module_recurrence():
    from ray_tpu.rllib.core.rl_module import RLModuleSpec

    spec = RLModuleSpec(obs_dim=6, action_dim=3, use_lstm=True,
                        lstm_cell_size=32)
    mod = spec.build()
    assert type(mod).__name__ == "LSTMModule"
    params = mod.init(jax.random.key(0))
    seq = jnp.ones((7, 5, 6))
    out, state = mod.forward_recurrent(params, seq, mod.initial_state(5))
    assert out["logits"].shape == (7, 5, 3)
    assert state[0].shape == (5, 32) and state[1].shape == (5, 32)
    # state carries information: perturbing it changes the output
    out2, _ = mod.forward_recurrent(params, seq,
                                    (state[0] + 1.0, state[1]))
    assert not bool(jnp.allclose(out["logits"][0], out2["logits"][0]))
    # gradient flows through the scan
    grads = jax.grad(lambda p: mod.forward_recurrent(
        p, seq, mod.initial_state(5))[0]["logits"].sum())(params)
    assert all(bool(jnp.isfinite(g).all())
               for g in jax.tree.leaves(grads))
    # stateless facade for the env-runner path
    single = mod.forward(params, jnp.ones((6,)))
    assert single["logits"].shape == (3,)


def test_lstm_can_remember():
    """Supervised memory task: the label is the FIRST step's sign, queried
    at the last step — impossible without recurrent state."""
    import optax

    from ray_tpu.rllib.core.rl_module import RLModuleSpec

    spec = RLModuleSpec(obs_dim=2, action_dim=2, use_lstm=True,
                        lstm_cell_size=16, hiddens=(16,))
    mod = spec.build()
    params = mod.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    T, B = 6, 64
    first = rng.choice([-1.0, 1.0], B).astype(np.float32)
    X = np.zeros((T, B, 2), np.float32)
    X[0, :, 0] = first
    X[1:, :, 1] = 1.0  # uninformative filler
    y = (first > 0).astype(np.int32)
    tx = optax.adam(5e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss(p):
            out, _ = mod.forward_recurrent(p, X, mod.initial_state(B))
            logps = jax.nn.log_softmax(out["logits"][-1])
            return -jnp.mean(jnp.take_along_axis(logps, y[:, None], axis=1))

        l, g = jax.value_and_grad(loss)(params)
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt, l

    losses = []
    for i in range(300):
        params, opt, l = step(params, opt)
        losses.append(float(l))
    assert losses[-1] < 0.1, losses[-1]


def test_apex_distributed_replay_learns_chain():
    """Ape-X: replay lives in a dedicated actor, runners explore on an
    epsilon ladder — and it still learns (reward-gated)."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    from tests.test_rllib_learning import ChainEnv

    from ray_tpu.rllib import ApexDQNConfig

    cfg = (ApexDQNConfig()
           .environment(ChainEnv)
           .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                        rollout_fragment_length=24)
           .training(lr=1e-3, train_batch_size=64, gamma=0.97)
           .debugging(seed=0))
    cfg.epsilon = [(0, 1.0), (10000, 0.05)]
    cfg.num_steps_sampled_before_learning_starts = 400
    cfg.target_network_update_freq = 500
    cfg.training_intensity = 4.0
    algo = cfg.build()
    try:
        eps = algo._runner_epsilons()
        assert len(eps) == 2 and eps[0] > eps[1]  # exploration ladder
        best = -np.inf
        for i in range(100):
            r = algo.train()
            ret = r.get("episode_return_mean")
            if ret is not None:
                best = max(best, ret)
            if i == 3:
                assert r["replay_actor_size"] > 0  # replay is off-driver
            if best >= 0.5:
                break
        assert best >= 0.5, f"ApexDQN failed to learn: best={best}"
    finally:
        algo.stop()
        ray_tpu.shutdown()
