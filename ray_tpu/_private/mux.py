"""Multiplexed direct-call plane (ISSUE 11).

One data/control session per peer PROCESS, carrying every actor channel,
lease-pool connection and owner callback channel as a logical STREAM
over the shared connection — the role gRPC's HTTP/2 streams play for the
reference's core_worker↔core_worker direct calls (reference:
``src/ray/rpc/worker/core_worker_client_pool.h`` caches ONE client per
worker address; ``direct_actor_task_submitter.h`` rides it per actor).

Pieces:

- :class:`MuxSession` — owns the underlying :class:`AsyncRpcClient`
  (ctrl socket), a fair round-robin outbound scheduler across streams,
  the session-scoped BatchItems router, and (same-node peers) the shm
  doorbell lane from :mod:`shm_rpc`.
- :class:`MuxStream` — the per-channel facade handed to callers. API
  mirrors the AsyncRpcClient subset the submitters use (``call`` /
  ``call_future`` / ``push`` / ``push_nowait`` / ``close`` …), so the
  actor and lease pipelines did not have to change shape. Closing a
  stream fails only ITS in-flight calls with a typed
  :class:`StreamClosedError`; the session and its sibling streams
  survive (the old per-actor ``client.close()`` tore down the whole
  socket).
- :class:`MuxPool` — sessions keyed ``(host, port)`` with the same
  race-guarded connect discipline as ``protocol.ConnectionPool``.
- ``_FrameOrderer`` + ``ShmServerDemux`` / ``ShmConnection`` — when a
  shm lane is attached, every frame of the session (BOTH lanes) carries
  a per-direction session seq ``q``; the receiving edge dispatches in
  ``q`` order, so a frame that fell back to TCP (oversized / ring full)
  can never be overtaken by a later shm frame. A seq missing past
  ``shm_rpc_order_gap_s`` (a fault-injected drop on one lane) is given
  up on instead of stalling the session forever.

Fairness: a chatty stream queueing thousands of frames shares the wire
in ``direct_call_fair_frames_per_round`` quanta, so a sibling's single
call dispatches within one quantum instead of behind the whole backlog.

MUST NOT import jax (driver AND parked workers import this module).
"""

from __future__ import annotations

import asyncio
import os
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu._private import shm_rpc
from ray_tpu._private.config import CONFIG
from ray_tpu._private.protocol import (
    AsyncRpcClient, ConnectionLost, pack)
from ray_tpu._private.async_util import hold_task, spawn_tracked


class StreamClosedError(ConnectionLost):
    """This stream was closed (actor died/restarting, lease dropped)
    while the call was in flight; the session itself is still up."""


# Mux-plane counters (ray_tpu_mux_* gauges + CLI "Direct-call plane").
MUX_STATS: Dict[str, int] = {
    "sessions_opened": 0,
    "streams_opened": 0,
    "streams_closed": 0,
    "fair_rounds": 0,      # flush rounds that had >1 stream queued
}


def route_batch_items(batches: Dict[int, Callable], payload: Any) -> None:
    """Dispatch one BatchItems push to its batch's per-item callback —
    THE one implementation of the wire contract, shared by mux sessions
    and plain per-channel clients (attach_batch_router)."""
    cb = batches.get((payload or {}).get("b"))
    if cb is not None:
        for i, reply in payload.get("xs", ()):
            cb(i, reply)


def attach_batch_router(client) -> Dict[int, Callable]:
    """Route streamed BatchItem pushes on a PLAIN client to their
    batch's per-item callback (mux streams get the session router at
    creation instead). One sync push handler per connection; batches
    register/unregister by id from ``client.next_batch_id()``."""
    batches: Dict[int, Callable] = {}

    def on_push(method, payload):
        if method == "BatchItems":
            route_batch_items(batches, payload)

    client.set_push_handler(on_push)
    client._stream_batches = batches
    return batches


class _FrameOrderer:
    """Per-direction dispatch orderer for a dual-lane session: frames
    carry a contiguous seq ``q``; out-of-order arrivals (one lane raced
    the other) are held until the gap fills, bounded by ``gap_s``."""

    __slots__ = ("_loop", "_deliver", "_gap_s", "expected", "_held",
                 "_timer", "closed")

    def __init__(self, loop, deliver: Callable[[Dict], None],
                 gap_s: float):
        self._loop = loop
        self._deliver = deliver
        self._gap_s = max(gap_s, 0.05)
        self.expected = 1
        self._held: Dict[int, Dict] = {}
        self._timer = None
        self.closed = False

    def feed(self, msg: Dict) -> None:
        if self.closed:
            return
        q = msg.get("q")
        if q is None or q < self.expected:
            # unstamped (pre-attach) or already-given-up-on: dispatch now
            self._deliver(msg)
            return
        if q == self.expected:
            self.expected += 1
            self._deliver(msg)
            while self.expected in self._held:
                _t, nxt = self._held.pop(self.expected)
                self.expected += 1
                self._deliver(nxt)
            if not self._held and self._timer is not None:
                self._timer.cancel()
                self._timer = None
            return
        self._held[q] = (time.monotonic(), msg)
        if self._timer is None:
            self._timer = self._loop.call_later(self._gap_s,
                                                self._gap_flush)

    def _gap_flush(self) -> None:
        """A seq never arrived (a fault rule ate one lane's frame).
        Dispatching the held tail out of order beats a wedged session —
        the missing frame's caller still gets its own timeout. The
        give-up clock runs from when the CURRENT oldest hold appeared:
        a timer armed for an earlier, since-filled gap must re-arm, not
        flush fresher holds after only a fraction of the window."""
        self._timer = None
        if self.closed or not self._held:
            return
        now = time.monotonic()
        oldest = min(t for t, _m in self._held.values())
        remaining = self._gap_s - (now - oldest)
        if remaining > 0.001:
            self._timer = self._loop.call_later(remaining,
                                                self._gap_flush)
            return
        shm_rpc.SHM_STATS["order_gap_flushes"] += 1
        for q in sorted(self._held):
            _t, msg = self._held.pop(q)
            if q >= self.expected:
                self.expected = q + 1
            self._deliver(msg)

    def close(self) -> None:
        self.closed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._held.clear()


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


class MuxStream:
    """One logical channel over a shared session. API-compatible with the
    AsyncRpcClient subset the task/actor submitters use."""

    __slots__ = ("session", "sid", "label", "closed", "_outq", "_queued",
                 "_pending", "_stream_batches")

    def __init__(self, session: "MuxSession", sid: int, label: str):
        self.session = session
        self.sid = sid
        self.label = label
        self.closed = False
        self._outq: deque = deque()
        self._queued = False  # present in the session's fair rotation?
        self._pending: set = set()
        # session-scoped BatchItems router: sibling streams share the
        # dict, ids come from next_batch_id() so they can never collide
        self._stream_batches = session._batches

    # ------------------------------------------------------------ client API
    @property
    def connected(self) -> bool:
        return (not self.closed and self.session.client is not None
                and self.session.client.connected)

    def next_batch_id(self) -> int:
        return self.session.next_batch_id()

    def call_future(self, method: str, payload: Any) -> asyncio.Future:
        """Loop-thread only (same contract as AsyncRpcClient)."""
        session = self.session
        client = session.client
        if self.closed:
            fut = session.loop.create_future()
            fut.set_exception(StreamClosedError(
                f"stream {self.label or self.sid} closed"))
            return fut
        if client is None or not client.connected:
            fut = session.loop.create_future()
            fut.set_exception(ConnectionLost("not connected"))
            return fut
        req_id, fut = client.register_call()
        self._track(fut)
        session.enqueue(self, {"m": method, "i": req_id, "p": payload,
                               "s": self.sid})
        return fut

    async def call(self, method: str, payload: Any,
                   timeout: Optional[float] = None) -> Any:
        fut = self.call_future(method, payload)
        if timeout:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    def push_nowait(self, method: str, payload: Any) -> None:
        if self.closed or self.session.client is None:
            return
        self.session.enqueue(self, {"m": method, "i": 0, "p": payload,
                                    "s": self.sid})

    async def push(self, method: str, payload: Any) -> None:
        self.push_nowait(method, payload)

    def start_idle_monitor(self, idle_s: float,
                           ping_method: str = "Ping") -> None:
        """Session-level: first stream to ask arms it for everyone."""
        if self.session.client is not None:
            self.session.client.start_idle_monitor(idle_s, ping_method)

    # ------------------------------------------------------------- lifecycle
    def _track(self, fut: asyncio.Future) -> None:
        self._pending.add(fut)
        fut.add_done_callback(self._pending.discard)

    def close(self) -> None:
        """Per-stream close: fail THIS stream's in-flight calls, drop its
        queued frames — the session and sibling streams stay up."""
        self.session.close_stream(self)

    def close_soon(self) -> None:
        self.close()

    async def aclose(self) -> None:
        self.close()


class MuxSession:
    """One peer process: the shared ctrl client + stream bookkeeping +
    (same-node) the shm doorbell lane."""

    def __init__(self, pool: "MuxPool", host: str, port: int):
        self.pool = pool
        self.host = host
        self.port = port
        self.client: Optional[AsyncRpcClient] = None
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.streams: Dict[int, MuxStream] = {}
        self._shared: Dict[str, MuxStream] = {}
        self.peer_node_id: Optional[str] = None
        self._next_sid = 0
        self._batches: Dict[int, Callable] = {}
        self._batch_seq = 0
        # fair outbound scheduler
        self._active: deque = deque()
        self._flush_armed = False
        # shm lane state
        self.lane: Optional[shm_rpc.ShmLane] = None
        self._orderer: Optional[_FrameOrderer] = None
        self._out_seq = 0
        self.closed = False

    def next_batch_id(self) -> int:
        self._batch_seq += 1
        return self._batch_seq

    # ---------------------------------------------------------------- open
    async def open_session(self, shm_node_id: Optional[str],
                   shm_store_dir: Optional[str]) -> None:
        self.loop = asyncio.get_running_loop()
        client = AsyncRpcClient()
        await client.connect_tcp(self.host, self.port)
        client.set_push_handler(self._on_push)
        client.start_idle_monitor(CONFIG.client_idle_deadline_s)
        self.client = client
        MUX_STATS["sessions_opened"] += 1
        # reap-on-death: a session whose peer exited must release its
        # lane fds/mmaps promptly (a churny 400-actor run would pin ~4
        # fds per dead peer until a lazy prune otherwise)
        spawn_tracked(self._watch_client(), "mux-session-watch")
        if shm_node_id and shm_store_dir and CONFIG.shm_rpc_enabled:
            try:
                await self._attach_shm(shm_node_id, shm_store_dir)
            except Exception:
                shm_rpc.SHM_STATS["attach_declined"] += 1
                # clean TCP fallback: the session works without the lane
                self.client._mux_feed = None
                if self._orderer is not None:
                    self._orderer.close()
                    self._orderer = None
                # the failure may be a TIMEOUT after the server already
                # committed its half — it would then sink every small
                # reply into a ring nobody reads. ShmDetach tears that
                # down; TCP FIFO guarantees it lands before any stream
                # call this session will ever make.
                try:
                    self.client.push_nowait("ShmDetach", {})
                except Exception:
                    pass

    async def _attach_shm(self, node_id: str, store_dir: str) -> None:
        """Rendezvous: WE create the rings + doorbell FIFOs under the
        store arena, the server maps them during the ShmAttach RPC, and
        the names are unlinked once both sides hold fds. Any failure
        leaves the session on pure TCP."""
        token = os.urandom(8).hex()
        paths = shm_rpc.lane_paths(store_dir, token)
        cap = int(CONFIG.shm_rpc_ring_bytes)
        tx = rx = None
        rx_bell_fd = tx_bell_fd = None
        try:
            tx = shm_rpc.ShmRing(paths["ring_c2s"], cap, create=True)
            rx = shm_rpc.ShmRing(paths["ring_s2c"], cap, create=True)
            shm_rpc.make_fifo(paths["bell_c2s"])
            shm_rpc.make_fifo(paths["bell_s2c"])
            # our read end must exist before the server opens its write
            # end (O_WRONLY|O_NONBLOCK is ENXIO without a reader)
            rx_bell_fd = shm_rpc.open_bell_read(paths["bell_s2c"])
            # the reorder stage must be live BEFORE any stamped frame can
            # arrive (the server stamps from its first post-attach reply)
            self._orderer = _FrameOrderer(
                self.loop, self._deliver_inbound,
                float(CONFIG.shm_rpc_order_gap_s))
            self.client._mux_feed = self._orderer.feed
            reply = await self.client.call(
                "ShmAttach",
                {"paths": paths, "node_id": node_id, "ring_bytes": cap},
                timeout=CONFIG.shm_rpc_attach_timeout_s)
            if not (reply or {}).get("ok"):
                raise ConnectionLost(
                    f"shm attach declined: {(reply or {}).get('reason')}")
            tx_bell_fd = shm_rpc.open_bell_write(paths["bell_c2s"])
            self.lane = shm_rpc.ShmLane(
                self.loop, tx=tx, rx=rx, tx_bell_fd=tx_bell_fd,
                rx_bell_fd=rx_bell_fd, on_frame=self._on_shm_frame)
            shm_rpc.SHM_STATS["attach_ok"] += 1
        except BaseException:
            for ring in (tx, rx):
                if ring is not None:
                    ring.close()
            for fd in (rx_bell_fd, tx_bell_fd):
                if fd is not None:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
            raise
        finally:
            shm_rpc.unlink_lane_paths(paths)

    async def _watch_client(self) -> None:
        task = getattr(self.client, "_read_task", None)
        if task is None:
            return
        await asyncio.wait({task})
        if not self.closed:
            self.close()
            pool = self.pool
            if pool is not None and \
                    pool._sessions.get((self.host, self.port)) is self:
                pool._sessions.pop((self.host, self.port), None)

    # ------------------------------------------------------------- inbound
    def _deliver_inbound(self, msg: Dict) -> None:
        client = self.client
        if client is None:
            return
        client.last_recv = time.monotonic()
        client._deliver_msg(msg)

    def _on_shm_frame(self, frame: bytes) -> None:
        msg = msgpack.unpackb(frame, raw=False, strict_map_key=False)
        orderer = self._orderer
        if orderer is not None and "q" in msg:
            orderer.feed(msg)
        else:
            self._deliver_inbound(msg)

    def _on_push(self, method: str, payload: Any):
        if method == "BatchItems":
            route_batch_items(self._batches, payload)

    # ------------------------------------------------------------ outbound
    def enqueue(self, stream: MuxStream, msg: Dict) -> None:
        stream._outq.append(msg)
        if not stream._queued:
            stream._queued = True
            self._active.append(stream)
        if not self._flush_armed:
            self._flush_armed = True
            self.loop.call_soon(self._flush)

    def _flush(self) -> None:
        """Fair drain: round-robin over queued streams, up to the quantum
        per turn, so a chatty stream's backlog interleaves with (instead
        of preceding) its siblings' frames in the combined write — the
        receiver dispatches in arrival order, so interleaving here bounds
        a sibling's dispatch delay to one quantum."""
        self._flush_armed = False
        quantum = max(1, int(CONFIG.direct_call_fair_frames_per_round))
        if len(self._active) > 1:
            MUX_STATS["fair_rounds"] += 1
        active = self._active
        while active:
            stream = active.popleft()
            outq = stream._outq
            n = 0
            while outq and n < quantum:
                self._send_now(outq.popleft())
                n += 1
            if outq:
                active.append(stream)
            else:
                stream._queued = False

    def _send_now(self, msg: Dict) -> None:
        client = self.client
        if client is None:
            return
        lane = self.lane
        if lane is not None and not lane.closed:
            # lane attached: EVERY frame (both lanes) is seq-stamped so
            # the server's reorder stage restores single-stream order
            self._out_seq += 1
            msg["q"] = self._out_seq
            body = pack(msg)
            if len(body) - 4 <= int(CONFIG.shm_rpc_max_frame_bytes):
                if lane.try_send(body[4:]):
                    return
            else:
                shm_rpc.SHM_STATS["fallback_oversize"] += 1
            client._send_frame(body, msg.get("m"))
            return
        client.send_msg_nowait(msg)

    # ------------------------------------------------------------ lifecycle
    def open_stream(self, label: str = "") -> MuxStream:
        self._next_sid += 1
        stream = MuxStream(self, self._next_sid, label)
        self.streams[stream.sid] = stream
        MUX_STATS["streams_opened"] += 1
        return stream

    def shared_stream(self, label: str = "owner") -> MuxStream:
        """Long-lived singleton channel per purpose (the owner-callback
        channel every worker keeps to each peer): callers share one
        stream instead of opening one per RPC."""
        stream = self._shared.get(label)
        if stream is None or stream.closed:
            stream = self.open_stream(label)
            self._shared[label] = stream
        return stream

    def close_stream(self, stream: MuxStream) -> None:
        if stream.closed:
            return
        stream.closed = True
        self.streams.pop(stream.sid, None)
        stream._outq.clear()
        MUX_STATS["streams_closed"] += 1
        err = StreamClosedError(
            f"stream {stream.label or stream.sid} closed")
        for fut in list(stream._pending):
            if not fut.done():
                fut.set_exception(err)
        stream._pending.clear()

    def close(self) -> None:
        """Session teardown (peer death verdict / pool drop): the
        client's close fails every stream's pending future with
        ConnectionLost — no per-stream hang."""
        if self.closed:
            return
        self.closed = True
        if self.lane is not None:
            self.lane.close()
            self.lane = None
        if self._orderer is not None:
            self._orderer.close()
            self._orderer = None
        for stream in list(self.streams.values()):
            stream.closed = True
            stream._outq.clear()
        self.streams.clear()
        if self.client is not None:
            self.client.close_soon()

    async def aclose(self) -> None:
        client = self.client
        self.closed = True
        if self.lane is not None:
            self.lane.close()
            self.lane = None
        if self._orderer is not None:
            self._orderer.close()
            self._orderer = None
        self.streams.clear()
        if client is not None:
            await client.aclose()


class MuxPool:
    """Sessions keyed (host, port) with race-guarded opens (the
    ConnectionPool discipline — a lost connect race must not leak the
    loser's read loop). ``node_id_fn``/``store_dir_fn`` supply the local
    identity lazily (the worker learns both at registration)."""

    def __init__(self, node_id_fn: Callable[[], Optional[str]] = None,
                 store_dir_fn: Callable[[], Optional[str]] = None):
        self._sessions: Dict[Tuple[str, int], MuxSession] = {}
        self._locks: Dict[Tuple[str, int], asyncio.Lock] = {}
        self._node_id_fn = node_id_fn or (lambda: None)
        self._store_dir_fn = store_dir_fn or (lambda: None)

    async def session(self, host: str, port: int,
                      peer_node_id: Optional[str] = None) -> MuxSession:
        key = (host, port)
        sess = self._sessions.get(key)
        if sess and not sess.closed and sess.client and \
                sess.client.connected:
            return sess
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:
            sess = self._sessions.get(key)
            if sess and not sess.closed and sess.client and \
                    sess.client.connected:
                return sess
            if sess is not None:
                sess.close()
            sess = MuxSession(self, host, port)
            sess.peer_node_id = peer_node_id
            local_node = self._node_id_fn()
            shm_node = None
            shm_dir = None
            # attach ONLY on a positive node match: worker/owner addrs
            # all carry node_id; a "looks local" heuristic would pay
            # ring setup + a guaranteed decline against every same-host
            # AGENT session (agents serve no ShmAttach by design)
            if local_node and peer_node_id == local_node:
                shm_node = local_node
                shm_dir = self._store_dir_fn()
            await sess.open_session(shm_node, shm_dir)
            self._sessions[key] = sess
            return sess

    async def stream(self, host: str, port: int, label: str = "",
                     peer_node_id: Optional[str] = None) -> MuxStream:
        sess = await self.session(host, port, peer_node_id=peer_node_id)
        return sess.open_stream(label)

    def drop(self, host: str, port: int) -> None:
        sess = self._sessions.pop((host, port), None)
        if sess is not None:
            sess.close()

    def drop_node(self, node_id: str) -> None:
        """Cluster death verdict: close every session to the node NOW so
        pending calls fail fast instead of riding a partitioned socket
        (the PR 5 fail-fast contract, session-granular)."""
        for key, sess in list(self._sessions.items()):
            if sess.peer_node_id == node_id:
                self._sessions.pop(key, None)
                sess.close()

    def total_streams(self) -> int:
        return sum(len(s.streams) for s in self._sessions.values())

    def shm_sessions(self) -> int:
        return sum(1 for s in self._sessions.values()
                   if s.lane is not None and not s.lane.closed)

    async def aclose_all(self) -> None:
        sessions, self._sessions = list(self._sessions.values()), {}
        for sess in sessions:
            await sess.aclose()


# ---------------------------------------------------------------------------
# Server side
# ---------------------------------------------------------------------------


class ShmConnection:
    """Lane-aware reply connection handed to handlers once a session
    attached its shm lane: small replies/pushes ride the ring, oversized
    ones fall back to the TCP conn — every outbound frame seq-stamped so
    the client's reorder stage restores order. Shares ``meta`` with the
    TCP conn (handler bookkeeping keys on it)."""

    kind = "shm"

    def __init__(self, tcp_conn, demux: "ShmServerDemux"):
        self.tcp = tcp_conn
        self.meta = tcp_conn.meta
        self._demux = demux
        self._out_seq = 0

    @property
    def closed(self) -> bool:
        return self.tcp.closed

    def _try_lane(self, msg: Dict) -> bool:
        self._out_seq += 1
        msg["q"] = self._out_seq
        lane = self._demux.lane
        if lane is None or lane.closed:
            return False
        body = pack(msg)
        if len(body) - 4 > int(CONFIG.shm_rpc_max_frame_bytes):
            shm_rpc.SHM_STATS["fallback_oversize"] += 1
            return False
        return lane.try_send(body[4:])

    async def send(self, msg: Dict) -> None:
        if not self._try_lane(msg):
            await self.tcp.send(msg)

    def send_nowait(self, msg: Dict) -> None:
        if not self._try_lane(msg):
            self.tcp.send_nowait(msg)

    async def send_raw(self, req_id: int, raw) -> None:
        # bulk bodies keep the TCP data path (sliced writes + drain);
        # unstamped — raw replies resolve by req id, no ordering needs
        await self.tcp.send_raw(req_id, raw)

    async def push(self, method: str, payload: Any) -> None:
        await self.send({"m": method, "i": 0, "p": payload})

    def push_nowait(self, method: str, payload: Any) -> None:
        self.send_nowait({"m": method, "i": 0, "p": payload})

    def close(self) -> None:
        self.tcp.close()


class ShmServerDemux:
    """Installed as ``conn.mux_demux`` on the accepted TCP connection:
    funnels BOTH lanes' inbound frames through one reorder stage and
    dispatches with the lane-aware :class:`ShmConnection`, so per-caller
    execution chains (keyed on the conn object) stay coherent across
    lanes."""

    def __init__(self, server, tcp_conn, loop, tx: shm_rpc.ShmRing,
                 rx: shm_rpc.ShmRing, tx_bell_fd: int, rx_bell_fd: int):
        self._server = server
        self._loop = loop
        self.conn = ShmConnection(tcp_conn, self)
        self.lane = shm_rpc.ShmLane(
            loop, tx=tx, rx=rx, tx_bell_fd=tx_bell_fd,
            rx_bell_fd=rx_bell_fd, on_frame=self._on_shm_frame)
        self._orderer = _FrameOrderer(
            loop, self._dispatch, float(CONFIG.shm_rpc_order_gap_s))

    def feed_tcp(self, msg: Dict) -> None:
        if "q" in msg:
            self._orderer.feed(msg)
        else:
            self._dispatch(msg)

    def _on_shm_frame(self, frame: bytes) -> None:
        msg = msgpack.unpackb(frame, raw=False, strict_map_key=False)
        self.feed_tcp(msg)

    def _dispatch(self, msg: Dict) -> None:
        hold_task(self._loop.create_task(
            self._server._dispatch(self.conn, msg)), "rpc-dispatch")

    def close(self) -> None:
        self.lane.close()
        self._orderer.close()


async def handle_shm_attach(server, conn, payload: Dict,
                            node_id: str, store_dir: Optional[str]
                            ) -> Dict:
    """ShmAttach handler body (registered on every direct server): map
    the client-created rings/FIFOs and switch the connection onto the
    lane-aware demux. Any refusal is a plain ``ok=False`` — the client
    then runs the session on pure TCP."""
    def decline(reason: str) -> Dict:
        shm_rpc.SHM_STATS["attach_declined"] += 1
        return {"ok": False, "reason": reason}

    # post-attach dispatches hand handlers the lane-aware wrapper; the
    # demux hook and detach mark live on the underlying TCP conn
    conn = getattr(conn, "tcp", conn)
    if not CONFIG.shm_rpc_enabled:
        return decline("disabled")
    if conn.mux_demux is not None:
        return decline("already attached")
    if conn.meta.get("shm_detached"):
        # the client's ShmDetach overtook this attach's dispatch (its
        # attach timer expired while we were queued): committing now
        # would sink replies into a ring the client already abandoned
        return decline("client detached")
    if not node_id or (payload or {}).get("node_id") != node_id:
        return decline("cross-node")
    if not store_dir or not os.path.isdir(store_dir):
        return decline("no store arena")
    paths = (payload or {}).get("paths") or {}
    for key in ("ring_c2s", "ring_s2c", "bell_c2s", "bell_s2c"):
        p = paths.get(key)
        if not p or not shm_rpc.path_in_dir(p, store_dir):
            return decline(f"bad path for {key}")
    rx = tx = None
    rx_bell_fd = tx_bell_fd = None
    try:
        # client→server ring: we consume; server→client: we produce
        rx = shm_rpc.ShmRing(paths["ring_c2s"])
        tx = shm_rpc.ShmRing(paths["ring_s2c"])
        rx_bell_fd = shm_rpc.open_bell_read(paths["bell_c2s"])
        # the client's read end is already open (protocol order)
        tx_bell_fd = shm_rpc.open_bell_write(paths["bell_s2c"])
    except Exception as e:
        for ring in (rx, tx):
            if ring is not None:
                ring.close()
        if rx_bell_fd is not None:
            try:
                os.close(rx_bell_fd)
            except OSError:
                pass
        return decline(f"map failed: {e!r}")
    demux = ShmServerDemux(server, conn, asyncio.get_running_loop(),
                           tx=tx, rx=rx, tx_bell_fd=tx_bell_fd,
                           rx_bell_fd=rx_bell_fd)
    if conn.meta.get("shm_detached"):
        # detach raced in while the rings were being mapped
        demux.close()
        return decline("client detached")
    conn.mux_demux = demux
    shm_rpc.SHM_STATS["attach_served"] = \
        shm_rpc.SHM_STATS.get("attach_served", 0) + 1
    return {"ok": True, "ring_bytes": rx.capacity}


async def handle_shm_detach(conn, payload: Dict) -> Dict:
    """Client gave up on the lane (attach timeout after this side may
    have committed): drop back to plain TCP dispatch and release the
    rings. Idempotent; also marks the conn so a still-queued attach
    cannot commit afterwards. ``conn`` may be the lane-aware wrapper
    when the lane was already committed — unwrap to the TCP conn."""
    tcp = getattr(conn, "tcp", conn)
    tcp.meta["shm_detached"] = True
    demux = getattr(tcp, "mux_demux", None)
    tcp.mux_demux = None
    if demux is not None:
        demux.close()
    return {"ok": True}
