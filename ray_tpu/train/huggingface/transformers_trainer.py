"""TransformersTrainer — HuggingFace transformers on the worker group
(reference: python/ray/train/huggingface/transformers — the
TransformersTrainer wrapper + prepare_trainer/RayTrainReportCallback; the
reference SURVEY §2.4 Train row lists it among the framework trainers).

The torch path: the user's ``train_loop_per_worker`` builds a
``transformers.Trainer`` and calls :func:`prepare_trainer` on it, which
injects a report callback bridging HF logging into ``session.report`` so
checkpointing/metrics flow through the framework like every other trainer.
Process-group setup is inherited from the torch backend (gloo on this
image; the JAX path is ``JaxTrainer`` — preferred on TPU).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.torch.config import TorchConfig
from ray_tpu.train.torch.torch_trainer import TorchTrainer


def _require_transformers():
    try:
        import transformers  # noqa: F401
    except ImportError as e:  # pragma: no cover - transformers is baked in
        raise ImportError(
            "TransformersTrainer requires the `transformers` package"
        ) from e


class RayTrainReportCallback:
    """transformers.TrainerCallback that forwards HF logs to
    session.report (reference: transformers/_transformers_utils.py
    RayTrainReportCallback)."""

    def __new__(cls):
        _require_transformers()
        import transformers

        class _Callback(transformers.TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                from ray_tpu.train._internal.session import get_session

                session = get_session()
                if session is None or not logs:
                    return
                metrics = {k: v for k, v in logs.items()
                           if isinstance(v, (int, float))}
                metrics["step"] = state.global_step
                metrics["epoch"] = float(state.epoch or 0)
                session.report(metrics)

        return _Callback()


def prepare_trainer(trainer):
    """Attach the report callback to a transformers.Trainer (reference:
    ray.train.huggingface.transformers.prepare_trainer)."""
    _require_transformers()
    trainer.add_callback(RayTrainReportCallback())
    return trainer


class TransformersTrainer(TorchTrainer):
    """Runs a HF transformers training loop on each worker; DDP via the
    gloo process group the torch backend initializes (reference:
    train/huggingface/transformers/transformers_trainer.py)."""

    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict], None],
        *,
        train_loop_config: Optional[Dict] = None,
        torch_config: Optional[TorchConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        _require_transformers()
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint,
            datasets=datasets,
        )
