"""StandardAutoscaler (reference: python/ray/autoscaler/_private/
autoscaler.py:171 — the update() reconcile loop: read load, launch to cover
unfulfilled demand, terminate idle nodes past the timeout).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import get_nodes_to_launch
from ray_tpu.autoscaler.sdk import REQUEST_RESOURCES_KEY

logger = logging.getLogger("ray_tpu.autoscaler")


class StandardAutoscaler:
    """One reconcile step per ``update()`` call.

    Demand signal: each agent heartbeats its queued (unfulfillable) lease
    requests to the head, exposed through the cluster view (gcs.py
    ``_cluster_view``) — plus explicit ``sdk.request_resources`` entries from
    the head KV.
    """

    def __init__(
        self,
        config: Dict,
        provider: NodeProvider,
        gcs_call: Callable[[str, Dict], object],
    ):
        self.config = config
        self.provider = provider
        self.gcs_call = gcs_call
        self.idle_timeout_s = config.get("idle_timeout_minutes", 5.0) * 60
        self.max_workers = config.get("max_workers", 8)
        self.node_types: Dict[str, Dict] = config.get(
            "available_node_types", {})
        # providers that derive capacity (TPU slice aggregates) expose it
        # through a hook, so the autoscaler does not depend on sharing the
        # same mutable config dict object with the provider
        hook = getattr(provider, "node_type_resources", None)
        if hook is not None:
            for name, spec in self.node_types.items():
                derived = hook(name)
                if derived:
                    spec.setdefault("resources", dict(derived.get(
                        "resources", {})))
                    spec.setdefault("per_host_resources", dict(derived.get(
                        "per_host_resources", {})))
        self._idle_since: Dict[str, float] = {}
        self._launch_deadline: Dict[str, float] = {}
        self.num_launches = 0
        self.num_terminations = 0

    @property
    def BOOT_TIMEOUT_S(self) -> float:
        from ray_tpu._private.config import CONFIG

        return CONFIG.autoscaler_boot_timeout_s

    # ------------------------------------------------------------- helpers
    def _type_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for pid in self.provider.non_terminated_nodes():
            t = self.provider.node_tags(pid).get("node_type")
            if t:
                counts[t] = counts.get(t, 0) + 1
        return counts

    def _explicit_requests(self) -> List[Dict[str, int]]:
        try:
            raw = self.gcs_call("KvGet", {"ns": "autoscaler",
                                          "key": REQUEST_RESOURCES_KEY})
            if not raw:
                return []
            if isinstance(raw, bytes):
                raw = raw.decode()
            return json.loads(raw)
        except Exception:
            return []

    # -------------------------------------------------------------- update
    def update(self) -> Dict:
        view: Dict = self.gcs_call("GetClusterView", {}) or {}

        demands: List[Dict[str, int]] = []
        available: List[Dict[str, int]] = []
        runtime_to_provider: Dict[str, str] = {}
        runtime_ids: Dict[str, List[str]] = {}
        for pid in self.provider.non_terminated_nodes():
            runtime_ids[pid] = list(self.provider.runtime_node_ids(pid))
            for rid in runtime_ids[pid]:
                runtime_to_provider[rid] = pid
        totals: List[Dict[str, int]] = []
        for nid, n in view.items():
            demands.extend(n.get("pending", []))
            available.append(n["resources"]["available"])
            totals.append(n["resources"]["total"])

        # launched-but-not-yet-registered nodes absorb demand as synthetic
        # full-capacity pools, otherwise every tick during a node's ~seconds
        # of boot would launch another copy for the same demand
        from ray_tpu._private.resources import ResourceSet

        registered = set(view)
        now = time.monotonic()
        for pid in list(runtime_ids):
            rids = [r for r in runtime_ids[pid] if r in registered]
            expected = max(1, self.provider.expected_runtime_nodes(pid))
            if len(rids) >= expected:
                self._launch_deadline.pop(pid, None)
                continue
            deadline = self._launch_deadline.setdefault(
                pid, now + self.BOOT_TIMEOUT_S)
            if now > deadline:
                if not rids:
                    # nothing ever registered: the launch failed outright.
                    # Reclaim the provider node or it pins the node-type
                    # count (and cloud spend) forever with zero capacity.
                    logger.info(
                        "autoscaler: terminating failed launch %s", pid)
                    self.provider.terminate_node(pid)
                    self._launch_deadline.pop(pid, None)
                    self.num_terminations += 1
                continue  # boot presumed failed: stop counting its capacity
            ntype = self.provider.node_tags(pid).get("node_type")
            res = self.node_types.get(ntype, {}).get("resources")
            if res:
                # aggregate spec capacity, minus what already registered
                frac = 1.0 - len(rids) / expected
                wire = ResourceSet(
                    {k: v * frac for k, v in dict(res).items()}).to_wire()
                available.append(wire)
                totals.append(wire)

        counts = self._type_counts()
        total = sum(counts.values())

        # respect per-type min_workers before demand-driven launches
        to_launch: Dict[str, int] = {}
        for name, spec in self.node_types.items():
            deficit = spec.get("min_workers", 0) - counts.get(name, 0)
            if deficit > 0:
                to_launch[name] = deficit
        demand_launch = get_nodes_to_launch(
            self.node_types, demands, available, counts,
            self.max_workers, total + sum(to_launch.values()))
        for name, cnt in demand_launch.items():
            to_launch[name] = to_launch.get(name, 0) + cnt
        # sdk.request_resources pins express desired cluster *size*, so they
        # pack against node totals (busy nodes still count toward them)
        pin_launch = get_nodes_to_launch(
            self.node_types, self._explicit_requests(), totals, counts,
            self.max_workers, total + sum(to_launch.values()))
        for name, cnt in pin_launch.items():
            to_launch[name] = to_launch.get(name, 0) + cnt

        for name, cnt in to_launch.items():
            logger.info("autoscaler: launching %d x %s", cnt, name)
            from ray_tpu._private.event import report_event

            report_event("INFO", "AUTOSCALER_LAUNCH",
                         f"launching {cnt} x {name}",
                         node_type=name, count=cnt)
            try:
                launched = self.provider.create_node(name, cnt)
            except Exception as e:
                # a provider failure (quota, stockout, bad config) must
                # not kill the whole reconcile cycle — the provider has
                # already recorded its own backoff/rollback
                logger.warning("autoscaler: create_node(%s) failed: %s",
                               name, e)
                report_event("WARNING", "AUTOSCALER_LAUNCH_FAILED",
                             f"create_node {name}: {e}", node_type=name)
                continue
            self.num_launches += len(launched) \
                if isinstance(launched, list) else cnt

        # scale down: runtime-registered nodes idle past the timeout
        now = time.monotonic()
        terminated = []
        pins = self._explicit_requests()

        def _needed_for_pins(removed_nids) -> bool:
            """Would removing this whole set of nodes (all hosts of a
            slice at once) break a request_resources pin?"""
            if not pins:
                return False
            from ray_tpu.autoscaler.resource_demand_scheduler import _fit_on

            removed = set(removed_nids)
            pools = [ResourceSet.from_wire(n2["resources"]["total"])
                     for nid2, n2 in view.items() if nid2 not in removed]
            return any(not _fit_on(ResourceSet.from_wire(w), pools)
                       for w in pins)

        # group runtime nodes by provider node: a multi-host slice is one
        # atomic unit — it terminates only when EVERY host is idle past the
        # timeout (one busy host pins the whole slice)
        members: Dict[str, List[str]] = {}
        for nid in view:
            pid = runtime_to_provider.get(nid)
            if pid is not None:
                members.setdefault(pid, []).append(nid)
        for nid, n in view.items():
            pid = runtime_to_provider.get(nid)
            if pid is None:
                continue  # head or externally-managed node
            res = n["resources"]
            busy = res["available"] != res["total"] or n.get("pending")
            if busy:
                self._idle_since.pop(nid, None)
            else:
                self._idle_since.setdefault(nid, now)
        for pid, nids in members.items():
            all_idle = all(
                nid in self._idle_since
                and now - self._idle_since[nid] > self.idle_timeout_s
                for nid in nids)
            fully_up = len(nids) >= max(
                1, self.provider.expected_runtime_nodes(pid))
            # degraded multi-host slice (a host died and will not come
            # back): reapable once its re-boot deadline expired, else the
            # survivors would leak forever
            degraded = now > self._launch_deadline.get(pid, float("inf"))
            if not (all_idle and (fully_up or degraded)):
                continue
            ntype = self.provider.node_tags(pid).get("node_type")
            min_workers = self.node_types.get(ntype, {}).get("min_workers", 0)
            if (counts.get(ntype, 0) > min_workers and not to_launch
                    and not _needed_for_pins(nids)):
                logger.info("autoscaler: terminating idle node %s "
                            "(%d runtime nodes)", pid, len(nids))
                for nid in nids:
                    self.gcs_call("DrainNode", {"node_id": nid})
                self.provider.terminate_node(pid)
                counts[ntype] = counts.get(ntype, 0) - 1
                self.num_terminations += 1
                terminated.append(pid)
                for nid in nids:
                    self._idle_since.pop(nid, None)

        return {"launched": to_launch, "terminated": terminated,
                "num_nodes": sum(self._type_counts().values())}
