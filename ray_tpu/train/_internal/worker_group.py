"""WorkerGroup: the actor pool a trainer drives (reference:
python/ray/train/_internal/worker_group.py — RayTrainWorker :19,
WorkerGroup :102, actor creation :188, execute_async :235)."""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.session import (
    TrainingResult, get_session, in_session, init_session, shutdown_session)


@ray_tpu.remote
class RayTrainWorker:
    """One training worker process (one TPU host in the multi-host case)."""

    def __init__(self):
        self._train_thread: Optional[threading.Thread] = None
        self._session = None

    def execute(self, fn: Callable, *args, **kwargs) -> Any:
        return fn(*args, **kwargs)

    def node_meta(self) -> Dict:
        import os

        ctx = ray_tpu.get_runtime_context()
        return {"node_id": ctx.get_node_id(), "hostname": socket.gethostname(),
                "accelerators": ctx.get_accelerator_ids(),
                "pid": os.getpid()}

    def init_train_session(self, **kwargs) -> None:
        ckpt = kwargs.pop("checkpoint_path", None)
        self._session = init_session(
            checkpoint=Checkpoint(ckpt) if ckpt else None, **kwargs)

    def start_training(self, train_fn_blob: bytes) -> None:
        from ray_tpu._private import serialization as ser

        train_fn = ser.loads(train_fn_blob)
        session = self._session

        def run():
            try:
                train_fn(session.config)
                self._drop_session_refs(session)
                session.result_queue.put(TrainingResult(TrainingResult.DONE))
            except BaseException as e:  # noqa: BLE001 — shipped to driver
                import traceback

                self._drop_session_refs(session)
                session.result_queue.put(TrainingResult(
                    TrainingResult.ERROR,
                    error=f"{e!r}\n{traceback.format_exc()}"))

        self._train_thread = threading.Thread(target=run, daemon=True,
                                              name="train-loop")
        self._train_thread.start()

    def get_next(self, timeout: float = 3600.0,
                 release_upto: Optional[int] = None) -> Dict:
        """Block for the worker's next result (report/done/error).
        ``release_upto`` acks in-store checkpoint shards the driver has
        re-owned, releasing this worker's keepalive handles on them."""
        if release_upto is not None:
            self._session.release_shards(release_upto)
        return self._session.result_queue.get(timeout=timeout).to_wire()

    @staticmethod
    def _drop_session_refs(session) -> None:
        # release borrowed/held store refs before signaling DONE/ERROR:
        # the driver may kill this actor moments after consuming the
        # result, and RemoveBorrow only fires from a live process
        try:
            session.drop_object_refs()
        except Exception:
            pass

    def end_session(self) -> None:
        shutdown_session()
        self._session = None


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_group=None):
        self._num_workers = num_workers
        opts: Dict[str, Any] = {}
        res = dict(resources_per_worker)
        if "CPU" in res:
            opts["num_cpus"] = res.pop("CPU")
        if "TPU" in res:
            opts["num_tpus"] = res.pop("TPU")
        if res:
            opts["resources"] = res
        if placement_group is not None:
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy)

            self.workers = [
                RayTrainWorker.options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=placement_group,
                        placement_group_bundle_index=i),
                    **opts).remote()
                for i in range(num_workers)
            ]
        else:
            self.workers = [RayTrainWorker.options(**opts).remote()
                            for _ in range(num_workers)]

    def __len__(self) -> int:
        return self._num_workers

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers])

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def node_metas(self) -> List[Dict]:
        return ray_tpu.get([w.node_meta.remote() for w in self.workers])

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
