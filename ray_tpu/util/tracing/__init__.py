from ray_tpu.util.tracing.tracing_helper import (
    get_tracer,
    span,
    trace_enabled,
)

__all__ = ["get_tracer", "span", "trace_enabled"]
