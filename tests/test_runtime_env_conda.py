"""Conda runtime-env tests (VERDICT r2 missing #3; reference:
python/ray/_private/runtime_env/conda.py). Digest/YAML/command/prefix
resolution are tested offline as pure functions; env *materialization* is
tested against a fake conda binary that creates the prefix the way the
real solver would; and the e2e test starts a real worker under a fake env
prefix (bin/python → this interpreter), which needs no conda install at
all — the same offline pattern as the GKE REST and container suites."""

import json
import os
import stat
import sys

import pytest

from ray_tpu.runtime_env.conda import (
    create_env_command, emit_environment_yaml, ensure_conda_env,
    resolve_env_prefix, spec_digest, validate_conda_spec,
    worker_conda_command)
from ray_tpu.runtime_env.runtime_env import (
    RuntimeEnv, RuntimeEnvSetupError)


def make_fake_env(root, name="fakeenv"):
    """A prefix whose bin/python is this interpreter (symlink)."""
    prefix = root / name
    (prefix / "bin").mkdir(parents=True)
    os.symlink(sys.executable, prefix / "bin" / "python")
    return prefix


def make_bootable_env(root, name="taskenv"):
    """A prefix a worker can actually boot under: a venv whose
    site-packages chains to this interpreter's (a conda env likewise
    carries its own packages next to bin/python; --system-site-packages
    alone is not enough when the test interpreter is itself a venv —
    it would chain to the BASE python, missing this venv's packages)."""
    import glob
    import site
    import subprocess

    prefix = root / name
    subprocess.run(
        [sys.executable, "-m", "venv", "--system-site-packages",
         "--without-pip", str(prefix)], check=True, timeout=120)
    site_dir = glob.glob(str(prefix / "lib" / "python*" /
                             "site-packages"))[0]
    with open(os.path.join(site_dir, "_parent_env.pth"), "w") as f:
        f.write("\n".join(site.getsitepackages()))
    return prefix


class TestSpecValidation:
    def test_str_and_dict_ok(self):
        validate_conda_spec("myenv")
        validate_conda_spec({"dependencies": ["python=3.11",
                                              {"pip": ["requests"]}]})

    def test_empty_name_rejected(self):
        with pytest.raises(ValueError):
            validate_conda_spec("")

    def test_dict_needs_dependencies(self):
        with pytest.raises(ValueError, match="dependencies"):
            validate_conda_spec({"channels": ["conda-forge"]})

    def test_bad_dep_entry_rejected(self):
        with pytest.raises(TypeError):
            validate_conda_spec({"dependencies": [42]})

    def test_runtime_env_accepts_conda(self):
        env = RuntimeEnv(conda="base")
        assert env["conda"] == "base"
        with pytest.raises((ValueError, TypeError)):
            RuntimeEnv(conda={"channels": ["x"]})

    def test_container_conda_combo_rejected(self):
        with pytest.raises(ValueError, match="container.*conda"):
            RuntimeEnv(conda="base", container={"image": "x"})

    def test_pythonpath_has_no_empty_component(self, tmp_path,
                                               monkeypatch):
        monkeypatch.delenv("PYTHONPATH", raising=False)
        prefix = make_fake_env(tmp_path)
        _, env = worker_conda_command(str(prefix), {})
        assert not env["PYTHONPATH"].endswith(os.pathsep)
        assert "" not in env["PYTHONPATH"].split(os.pathsep)


class TestPureFunctions:
    SPEC = {"dependencies": ["python=3.11", "numpy",
                             {"pip": ["einops==0.7.0"]}],
            "channels": ["conda-forge"]}

    def test_digest_stable_and_order_sensitive_only_on_content(self):
        same = {"channels": ["conda-forge"],
                "dependencies": ["python=3.11", "numpy",
                                 {"pip": ["einops==0.7.0"]}]}
        assert spec_digest(self.SPEC) == spec_digest(same)
        assert spec_digest(self.SPEC) != spec_digest(
            {**self.SPEC, "dependencies": ["python=3.12"]})

    def test_yaml_emission_shape(self):
        text = emit_environment_yaml({**self.SPEC, "name": "e"})
        assert 'name: "e"' in text
        assert '  - "conda-forge"' in text
        assert '  - "python=3.11"' in text
        # nested pip block is indented under a "pip": key
        assert '  - "pip":' in text
        assert '    - "einops==0.7.0"' in text

    def test_create_command_conda_vs_micromamba(self):
        assert create_env_command("/u/bin/conda", "/p", "/f.yml") == \
            ["/u/bin/conda", "env", "create", "-p", "/p", "-f", "/f.yml"]
        assert create_env_command("/u/bin/micromamba", "/p", "/f.yml") == \
            ["/u/bin/micromamba", "create", "--yes", "-p", "/p",
             "-f", "/f.yml"]

    def test_worker_command_uses_env_python(self, tmp_path):
        prefix = make_fake_env(tmp_path)
        cmd, env = worker_conda_command(str(prefix),
                                        {"RAY_TPU_WORKER_ID": "abc"})
        assert cmd[0] == str(prefix / "bin" / "python")
        assert cmd[-1] == "ray_tpu._private.worker_process"
        import ray_tpu

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        assert env["PYTHONPATH"].startswith(pkg_parent)
        assert env["CONDA_PREFIX"] == str(prefix)
        assert env["PATH"].startswith(str(prefix / "bin"))
        assert env["RAY_TPU_WORKER_ID"] == "abc"


class TestPrefixResolution:
    def test_path_spec_resolves_directly(self, tmp_path):
        prefix = make_fake_env(tmp_path)
        assert resolve_env_prefix(str(prefix)) == str(prefix)

    def test_path_without_python_rejected(self, tmp_path):
        (tmp_path / "empty").mkdir()
        with pytest.raises(RuntimeEnvSetupError, match="bin/python"):
            resolve_env_prefix(str(tmp_path / "empty"))

    def test_named_env_found_via_envs_path(self, tmp_path, monkeypatch):
        envs = tmp_path / "envs"
        prefix = make_fake_env(envs, "research")
        monkeypatch.setenv("CONDA_ENVS_PATH", str(envs))
        assert resolve_env_prefix("research") == str(prefix)

    def test_unknown_name_raises(self, monkeypatch):
        monkeypatch.delenv("CONDA_ENVS_PATH", raising=False)
        monkeypatch.delenv("CONDA_PREFIX", raising=False)
        with pytest.raises(RuntimeEnvSetupError, match="not found"):
            resolve_env_prefix("definitely-not-an-env", binary=None)


class TestMaterialization:
    def fake_conda(self, tmp_path):
        """A stand-in solver: records its argv, then creates the prefix
        with bin/python like the real `conda env create` would."""
        script = tmp_path / "conda"
        log = tmp_path / "calls.jsonl"
        script.write_text(f"""#!{sys.executable}
import json, os, sys
args = sys.argv[1:]
with open({str(log)!r}, "a") as f:
    f.write(json.dumps(args) + "\\n")
p = args[args.index("-p") + 1]
os.makedirs(os.path.join(p, "bin"), exist_ok=True)
os.symlink({sys.executable!r}, os.path.join(p, "bin", "python"))
""")
        script.chmod(script.stat().st_mode | stat.S_IEXEC)
        return str(script), log

    def test_dict_spec_creates_and_caches(self, tmp_path):
        binary, log = self.fake_conda(tmp_path)
        spec = {"dependencies": ["python=3.11"]}
        p1 = ensure_conda_env(spec, str(tmp_path / "cache"), binary=binary)
        assert os.path.exists(os.path.join(p1, "bin", "python"))
        calls = [json.loads(l) for l in log.read_text().splitlines()]
        assert len(calls) == 1 and calls[0][:2] == ["env", "create"]
        # the yaml handed to the solver round-trips the dependencies
        yml = open(calls[0][calls[0].index("-f") + 1]).read()
        assert '"python=3.11"' in yml
        # second call: cache hit, no new solver invocation
        p2 = ensure_conda_env(spec, str(tmp_path / "cache"), binary=binary)
        assert p2 == p1
        assert len(log.read_text().splitlines()) == 1

    def test_no_binary_is_setup_error(self, tmp_path, monkeypatch):
        import ray_tpu.runtime_env.conda as conda_mod

        monkeypatch.setattr(conda_mod, "conda_binary", lambda: None)
        with pytest.raises(RuntimeEnvSetupError, match="no conda"):
            ensure_conda_env({"dependencies": ["x"]},
                             str(tmp_path / "cache"))

    def test_failed_create_cleans_up(self, tmp_path):
        bad = tmp_path / "badconda"
        bad.write_text(f"#!{sys.executable}\nraise SystemExit(1)\n")
        bad.chmod(bad.stat().st_mode | stat.S_IEXEC)
        with pytest.raises(RuntimeEnvSetupError, match="create failed"):
            ensure_conda_env({"dependencies": ["x"]},
                             str(tmp_path / "cache"), binary=str(bad))


class TestEndToEnd:
    def test_worker_runs_under_env_interpreter(self, tmp_path):
        """{"conda": <prefix>} must start the worker with the env's
        python — verified by sys.executable inside the task. The fake
        prefix's python is this interpreter by symlink, so no conda
        install is needed (reference's skip-if-no-conda tests can't run
        offline; this can)."""
        prefix = make_bootable_env(tmp_path, "taskenv")
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=2)
        try:
            @ray_tpu.remote(runtime_env={"conda": str(prefix)})
            def whereami():
                return sys.executable, os.environ.get("CONDA_PREFIX")

            exe, env_prefix = ray_tpu.get(whereami.remote(), timeout=120)
            assert exe == str(prefix / "bin" / "python")
            assert env_prefix == str(prefix)

            # a host-env task scheduled alongside must NOT ride the
            # conda-tagged worker (pool affinity)
            @ray_tpu.remote
            def host():
                return sys.executable

            assert ray_tpu.get(host.remote(), timeout=60) == sys.executable
        finally:
            ray_tpu.shutdown()

    def test_missing_env_fails_fast(self, tmp_path):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(num_cpus=2)
        try:
            @ray_tpu.remote(runtime_env={"conda": "no-such-env-anywhere"})
            def f():
                return 1

            with pytest.raises(Exception, match="not found|runtime_env"):
                ray_tpu.get(f.remote(), timeout=60)
        finally:
            ray_tpu.shutdown()
