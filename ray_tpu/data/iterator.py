"""DataIterator: batch-level consumption of a block stream.

Reference: python/ray/data/iterator.py (iter_batches, iter_torch_batches).
TPU-first addition: ``iter_jax_batches`` ships each batch to device —
optionally onto a ``NamedSharding`` so a data-parallel mesh gets its
per-device shards directly — with background prefetch so host→HBM transfer
overlaps the train step.

Ingest is stall-free end to end (ISSUE 12): the underlying block stream
(``Dataset._iter_blocks``) initiates the next
``DataContext.iter_prefetch_blocks`` blocks' pulls one batched
non-blocking WaitObjects window ahead of consumption, so the network
transfer of block N+1 overlaps decode/batch/device-put of block N; the
``_prefetch`` thread below then overlaps host→device transfer with the
consumer. Residual time blocked on pulls is reported as
``consumer_stall_s`` in ``ExecutorStats`` (visible via ``stats()``).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor


class _Batcher:
    """Re-chunk a stream of blocks into fixed-size batches."""

    def __init__(self, batch_size: Optional[int], drop_last: bool = False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._buffer: collections.deque = collections.deque()
        self._buffered_rows = 0

    def add(self, block: Block) -> None:
        n = BlockAccessor(block).num_rows()
        if n:
            self._buffer.append(block)
            # raylint: disable=R13 -- single-consumer protocol: one
            # _Batcher instance is only ever driven by the one iterator
            # that owns it; the two domains the linter sees are distinct
            # pipelines with distinct instances, never one shared batcher
            self._buffered_rows += n

    def next_batches(self, final: bool = False) -> Iterator[Block]:
        bs = self.batch_size
        if bs is None:
            while self._buffer:
                self._buffered_rows -= BlockAccessor(
                    self._buffer[0]).num_rows()
                yield self._buffer.popleft()
            return
        while self._buffered_rows >= bs:
            yield self._take(bs)
        if final and self._buffered_rows and not self.drop_last:
            yield self._take(self._buffered_rows)

    def _take(self, n: int) -> Block:
        parts = []
        got = 0
        while got < n:
            block = self._buffer.popleft()
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            if got + rows <= n:
                parts.append(block)
                got += rows
            else:
                need = n - got
                parts.append(acc.slice(0, need))
                self._buffer.appendleft(acc.slice(need, rows))
                got = n
        self._buffered_rows -= n
        return BlockAccessor.concat(parts)


class DataIterator:
    """Iterates batches over a (re-executable) stream of blocks."""

    def __init__(self, block_fn: Callable[[], Iterator[Block]],
                 stats_fn: Optional[Callable[[], str]] = None):
        self._block_fn = block_fn
        self._stats_fn = stats_fn

    def iter_blocks(self) -> Iterator[Block]:
        return self._block_fn()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        prefetch_batches: int = 1,
    ) -> Iterator[Any]:
        def gen():
            batcher = _Batcher(batch_size, drop_last)
            shuffler = (_LocalShuffler(local_shuffle_buffer_size,
                                       local_shuffle_seed)
                        if local_shuffle_buffer_size else None)
            source = self.iter_blocks()
            if shuffler is not None:
                source = shuffler.shuffle(source)
            for block in source:
                batcher.add(block)
                for b in batcher.next_batches():
                    yield BlockAccessor(b).to_batch(batch_format)
            for b in batcher.next_batches(final=True):
                yield BlockAccessor(b).to_batch(batch_format)

        if prefetch_batches and prefetch_batches > 0:
            return _prefetch(gen(), prefetch_batches)
        return gen()

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def iter_jax_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        dtypes: Optional[Dict[str, Any]] = None,
        sharding=None,
        device=None,
        drop_last: bool = True,
        local_shuffle_buffer_size: Optional[int] = None,
        prefetch_batches: int = 2,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as device-resident ``jax.Array``s.

        ``sharding`` may be a ``jax.sharding.Sharding`` (e.g. NamedSharding
        over the mesh's data axis) applied to every column; ``drop_last``
        defaults True because XLA recompiles on shape change.
        """
        import jax

        def to_device(batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                if sharding is not None:
                    out[k] = jax.device_put(v, sharding)
                elif device is not None:
                    out[k] = jax.device_put(v, device)
                else:
                    out[k] = jax.device_put(v)
            return out

        it = self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            prefetch_batches=0)
        return _prefetch(map(to_device, it), prefetch_batches)

    def iter_torch_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        dtypes: Optional[Dict[str, Any]] = None,
        device: Optional[str] = None,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        prefetch_batches: int = 2,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as torch tensors (reference: iterator.py
        iter_torch_batches; torch is CPU-only in this image)."""
        import torch

        def to_torch(batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
            out = {}
            for k, v in batch.items():
                if v.dtype == object:
                    out[k] = v  # strings/bytes stay numpy
                    continue
                t = torch.from_numpy(np.ascontiguousarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device:
                    t = t.to(device)
                out[k] = t
            return out

        it = self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            prefetch_batches=0)
        return _prefetch(map(to_torch, it), prefetch_batches)

    def materialize(self):
        from ray_tpu.data.dataset import from_blocks

        return from_blocks(list(self.iter_blocks()))

    def stats(self) -> str:
        return self._stats_fn() if self._stats_fn else ""


class _LocalShuffler:
    def __init__(self, buffer_rows: int, seed: Optional[int]):
        self.buffer_rows = buffer_rows
        self.rng = np.random.default_rng(seed)

    def shuffle(self, blocks: Iterator[Block]) -> Iterator[Block]:
        held = []
        held_rows = 0
        for block in blocks:
            held.append(block)
            held_rows += BlockAccessor(block).num_rows()
            if held_rows >= self.buffer_rows:
                merged = BlockAccessor.concat(held)
                acc = BlockAccessor(merged)
                yield acc.take_indices(self.rng.permutation(acc.num_rows()))
                held, held_rows = [], 0
        if held:
            merged = BlockAccessor.concat(held)
            acc = BlockAccessor(merged)
            yield acc.take_indices(self.rng.permutation(acc.num_rows()))


def _prefetch(it: Iterator, depth: int) -> Iterator:
    """Run the source iterator on a thread, buffering ``depth`` items.

    An abandoned consumer (``break`` mid-loop) closes this generator; the
    worker sees the stop flag on its next bounded put, closes the source
    iterator (which tears down the StreamingExecutor via its ``finally``)
    and exits instead of leaking a thread blocked on ``q.put``."""
    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    DONE = object()
    err: list = []
    stop = threading.Event()

    def worker():
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    break
        except BaseException as e:
            err.append(e)
        finally:
            if stop.is_set():
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
            q.put(DONE)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is DONE:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
