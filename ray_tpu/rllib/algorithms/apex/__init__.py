from ray_tpu.rllib.algorithms.apex.apex import ApexDQN, ApexDQNConfig

__all__ = ["ApexDQN", "ApexDQNConfig"]
