"""Datasources: each produces a list of read tasks (closures returning
blocks), one per file/fragment, so reads parallelize as tasks
(reference: python/ray/data/datasource/ + read_api.py).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix}" if suffix else "*")
            out.extend(sorted(f for f in glob.glob(pat, recursive=True)
                              if os.path.isfile(f)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class Datasource:
    """ABC (reference: datasource/datasource.py Datasource/Reader)."""

    name = "Datasource"

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    name = "Range"

    def __init__(self, n: int, tensor_shape: Optional[tuple] = None,
                 column: str = "id"):
        self.n = n
        self.tensor_shape = tensor_shape
        self.column = column

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        parallelism = max(1, min(parallelism, self.n or 1))
        tasks = []
        per = (self.n + parallelism - 1) // parallelism if self.n else 0
        for i in range(parallelism):
            lo, hi = i * per, min((i + 1) * per, self.n)
            if lo >= hi and self.n > 0:
                continue
            shape, col = self.tensor_shape, self.column

            def read(lo=lo, hi=hi):
                ids = np.arange(lo, hi, dtype=np.int64)
                if shape is None:
                    return {col: ids}
                data = np.broadcast_to(
                    ids.reshape((-1,) + (1,) * len(shape)),
                    (hi - lo,) + shape).astype(np.float64)
                return {col: np.ascontiguousarray(data)}

            tasks.append(read)
        return tasks or [lambda: {self.column: np.asarray([], np.int64)}]


class ItemsDatasource(Datasource):
    name = "FromItems"

    def __init__(self, items: List[Any]):
        self.items = items

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        from ray_tpu.data.block import BlockAccessor

        n = len(self.items)
        parallelism = max(1, min(parallelism, n or 1))
        per = (n + parallelism - 1) // parallelism if n else 0
        tasks = []
        for i in range(parallelism):
            chunk = self.items[i * per:(i + 1) * per]
            if not chunk and n > 0:
                continue
            tasks.append(lambda chunk=chunk: BlockAccessor.rows_to_block(chunk))
        return tasks or [lambda: BlockAccessor.rows_to_block([])]


class FileDatasource(Datasource):
    """One read task per file."""

    suffix: Optional[str] = None

    def __init__(self, paths, **read_kwargs):
        self.paths = _expand_paths(paths, self.suffix)
        self.read_kwargs = read_kwargs

    def read_file(self, path: str) -> Any:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[Callable[[], Any]]:
        return [lambda p=p: self.read_file(p) for p in self.paths]


class ParquetDatasource(FileDatasource):
    name = "ReadParquet"
    suffix = ".parquet"

    def read_file(self, path: str):
        import pyarrow.parquet as pq

        return pq.read_table(path, **self.read_kwargs)


class CSVDatasource(FileDatasource):
    name = "ReadCSV"
    suffix = ".csv"

    def read_file(self, path: str):
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path, **self.read_kwargs)


class JSONDatasource(FileDatasource):
    name = "ReadJSON"
    suffix = ".json"

    def read_file(self, path: str):
        import pyarrow.json as pajson

        return pajson.read_json(path, **self.read_kwargs)


class TextDatasource(FileDatasource):
    name = "ReadText"
    suffix = None

    def read_file(self, path: str):
        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        return {"text": np.asarray(lines, dtype=object)}


class BinaryDatasource(FileDatasource):
    name = "ReadBinary"
    suffix = None

    def read_file(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        return {"bytes": np.asarray([data], dtype=object),
                "path": np.asarray([path], dtype=object)}


class NumpyDatasource(FileDatasource):
    name = "ReadNumpy"
    suffix = ".npy"

    def read_file(self, path: str):
        return {"data": np.load(path)}


class TFRecordDatasource(FileDatasource):
    """tf.train.Example TFRecords via the built-in pure-Python codec
    (reference: data/datasource/tfrecords_datasource.py, minus the
    tensorflow dependency)."""

    name = "ReadTFRecords"
    suffix = ".tfrecord"

    def read_file(self, path: str):
        from ray_tpu.data._internal.tfrecord import read_tfrecord_file
        from ray_tpu.data.block import BlockAccessor

        rows = []
        for row in read_tfrecord_file(path):
            flat = {}
            for k, v in row.items():
                if isinstance(v, list):  # BytesList
                    flat[k] = v[0] if len(v) == 1 else v
                elif isinstance(v, np.ndarray) and v.size == 1:
                    flat[k] = v[0]
                else:
                    flat[k] = v
            rows.append(flat)
        return BlockAccessor.rows_to_block(rows)


class WebDatasetDatasource(FileDatasource):
    """WebDataset tar shards (reference: data/datasource/
    webdataset_datasource.py): samples are groups of tar members sharing
    a basename — ``0001.jpg`` + ``0001.cls`` -> one row with columns
    ``jpg``, ``cls`` (+ ``__key__``). Decoding: .json -> object,
    common image suffixes -> HWC uint8 via PIL, text suffixes -> str,
    everything else raw bytes."""

    name = "ReadWebDataset"
    suffix = ".tar"

    IMAGE_EXTS = ("jpg", "jpeg", "png", "bmp", "webp")
    TEXT_EXTS = ("txt", "cls", "text")

    def _decode(self, ext: str, data: bytes):
        import io
        import json as _json

        if ext == "json":
            return _json.loads(data)
        if ext in self.IMAGE_EXTS:
            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
        if ext in self.TEXT_EXTS:
            return data.decode()
        return data

    def read_file(self, path: str):
        import tarfile

        from ray_tpu.data.block import BlockAccessor

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                base, _, ext = member.name.rpartition(".")
                if not base:
                    base, ext = member.name, ""
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                data = tf.extractfile(member).read()
                samples[base][ext.lower()] = self._decode(ext.lower(),
                                                          data)
        return BlockAccessor.rows_to_block(
            [samples[k] for k in order])


class ImageDatasource(FileDatasource):
    """Image files via PIL (reference: data/datasource/
    image_datasource.py): columns ``image`` (HWC uint8) + ``path``."""

    name = "ReadImages"
    suffix = None

    IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

    def __init__(self, paths, size=None, mode: str = "RGB", **kw):
        super().__init__(paths, **kw)
        self.paths = [p for p in self.paths
                      if p.lower().endswith(self.IMAGE_EXTS)]
        if not self.paths:
            raise FileNotFoundError(f"no image files in {paths!r}")
        self.size = size
        self.mode = mode

    def read_file(self, path: str):
        from PIL import Image

        img = Image.open(path)
        if self.mode:
            img = img.convert(self.mode)
        if self.size:
            img = img.resize((self.size[1], self.size[0]))
        arr = np.asarray(img)
        return {"image": arr[None], "path": np.asarray([path], object)}


# ------------------------------------------------------------------ writers
def write_parquet_fn(path: str):
    os.makedirs(path, exist_ok=True)

    def write(batch):
        import uuid

        import pyarrow.parquet as pq

        from ray_tpu.data.block import BlockAccessor

        table = BlockAccessor(BlockAccessor.batch_to_block(batch)).to_arrow()
        fn = os.path.join(path, f"part-{uuid.uuid4().hex[:12]}.parquet")
        pq.write_table(table, fn)
        return {"path": np.asarray([fn], dtype=object),
                "num_rows": np.asarray([table.num_rows])}

    return write


def write_tfrecords_fn(path: str):
    os.makedirs(path, exist_ok=True)

    def write(batch):
        import uuid

        from ray_tpu.data._internal.tfrecord import write_tfrecord_file
        from ray_tpu.data.block import BlockAccessor

        acc = BlockAccessor(BlockAccessor.batch_to_block(batch))
        fn = os.path.join(path, f"part-{uuid.uuid4().hex[:12]}.tfrecord")
        n = write_tfrecord_file(fn, acc.iter_rows())
        return {"path": np.asarray([fn], dtype=object),
                "num_rows": np.asarray([n])}

    return write


def write_csv_fn(path: str):
    os.makedirs(path, exist_ok=True)

    def write(batch):
        import uuid

        import pyarrow.csv as pacsv

        from ray_tpu.data.block import BlockAccessor

        table = BlockAccessor(BlockAccessor.batch_to_block(batch)).to_arrow()
        fn = os.path.join(path, f"part-{uuid.uuid4().hex[:12]}.csv")
        pacsv.write_csv(table, fn)
        return {"path": np.asarray([fn], dtype=object),
                "num_rows": np.asarray([table.num_rows])}

    return write


def write_json_fn(path: str):
    os.makedirs(path, exist_ok=True)

    def write(batch):
        import json
        import uuid

        from ray_tpu.data.block import BlockAccessor

        acc = BlockAccessor(BlockAccessor.batch_to_block(batch))
        fn = os.path.join(path, f"part-{uuid.uuid4().hex[:12]}.json")
        with open(fn, "w") as f:
            for row in acc.iter_rows():
                f.write(json.dumps(
                    {k: (v.tolist() if isinstance(v, np.ndarray)
                         else v.item() if isinstance(v, np.generic) else v)
                     for k, v in row.items()}) + "\n")
        return {"path": np.asarray([fn], dtype=object),
                "num_rows": np.asarray([acc.num_rows()])}

    return write


class SQLDatasource(Datasource):
    """DBAPI2 reads (reference: read_api.py:1902 read_sql — connection
    factory + query; parallelized by wrapping the query in LIMIT/OFFSET
    windows when a row count is obtainable, else a single task)."""

    name = "SQL"

    def __init__(self, sql: str, connection_factory):
        self.sql = sql.strip().rstrip(";")
        self.connection_factory = connection_factory

    def _count(self) -> Optional[int]:
        try:
            conn = self.connection_factory()
            try:
                cur = conn.cursor()
                cur.execute(f"SELECT COUNT(*) FROM ({self.sql}) AS _q")
                return int(cur.fetchone()[0])
            finally:
                conn.close()
        except Exception:
            return None

    def get_read_tasks(self, parallelism: int):
        import pyarrow as pa

        sql = self.sql
        factory = self.connection_factory

        def fetch(query: str):
            conn = factory()
            try:
                cur = conn.cursor()  # DBAPI2: execute lives on the cursor
                cur.execute(query)
                cols = [d[0] for d in cur.description]
                rows = cur.fetchall()
            finally:
                conn.close()
            data = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
            return pa.table(data)

        # LIMIT/OFFSET windows are only consistent when the scan order is
        # stable: without ORDER BY, engines may return rows in a different
        # order per execution and windows can overlap or drop rows
        lowered = sql.lower()
        if "order by" not in lowered or " limit " in f" {lowered} ":
            if parallelism > 1:
                import logging

                logging.getLogger("ray_tpu.data").info(
                    "read_sql: query has no ORDER BY; reading as one task "
                    "(windowed parallelism needs a stable order)")
            return [lambda: fetch(sql)]
        total = self._count()
        if not total or parallelism <= 1:
            return [lambda: fetch(sql)]
        parallelism = min(parallelism, total)
        chunk = -(-total // parallelism)
        tasks = []
        for i in range(parallelism):
            off = i * chunk
            if off >= total:
                break
            # append directly: a subquery's ORDER BY need not propagate to
            # the outer SELECT, which would defeat the stable-window premise
            q = f"{sql} LIMIT {chunk} OFFSET {off}"
            tasks.append(lambda q=q: fetch(q))
        return tasks


def _docs_to_block(docs: List[Dict]) -> Dict[str, List]:
    """Column-union a list of documents (a field first appearing
    mid-collection must not silently vanish)."""
    for d in docs:
        d.pop("_id", None)
    if not docs:
        return {"_empty": []}
    keys: List[str] = []
    for d in docs:
        for k in d:
            if k not in keys:
                keys.append(k)
    return {k: [d.get(k) for d in docs] for k in keys}


def _mongo_range_filters(split_points: List, lo, hi) -> List[Dict]:
    """[lo, p1), [p1, p2), ..., [pN, hi] range filters over _id
    (reference: mongo_datasource.py splits the collection by _id
    boundaries so each read task scans a disjoint slice)."""
    bounds = [lo] + list(split_points) + [hi]
    filters = []
    for i in range(len(bounds) - 1):
        f: Dict = {"_id": {"$gte": bounds[i]}}
        if i < len(bounds) - 2:
            f["_id"]["$lt"] = bounds[i + 1]
        else:
            f["_id"]["$lte"] = bounds[i + 1]
        filters.append(f)
    return filters


class MongoDatasource(Datasource):
    """MongoDB reads partitioned by _id ranges (reference:
    python/ray/data/datasource/mongo_datasource.py — each read task
    scans a disjoint _id slice so ``parallelism`` is honored).
    ``pymongo`` is not in this deployment's package set: construction
    composes offline and read tasks raise a clear ImportError at
    execution; ``_collection_factory`` injects a client for tests (the
    partitioned path executes against a fake collection)."""

    name = "Mongo"

    def __init__(self, uri: str, database: str, collection: str,
                 pipeline: Optional[List[Dict]] = None,
                 _collection_factory=None):
        self.uri = uri
        self.database = database
        self.collection = collection
        self.pipeline = pipeline or []
        self._collection_factory = _collection_factory

    def _collection(self):
        if self._collection_factory is not None:
            return self._collection_factory()
        try:
            import pymongo
        except ImportError as e:
            raise ImportError(
                "read_mongo requires `pymongo`, which is not installed "
                "in this environment") from e
        client = pymongo.MongoClient(self.uri)
        return client[self.database][self.collection]

    def _split_bounds(self, parallelism: int):
        """(lo, hi, interior split points) via $bucketAuto server-side
        sampling; (None, None, []) => unsplittable (empty/tiny/gated)."""
        try:
            coll = self._collection()
            buckets = list(coll.aggregate([
                {"$bucketAuto": {"groupBy": "$_id",
                                 "buckets": max(1, parallelism)}}]))
        except ImportError:
            raise
        except Exception:
            return None, None, []
        if not buckets:
            return None, None, []
        lo = buckets[0]["_id"]["min"]
        hi = buckets[-1]["_id"]["max"]
        points = [b["_id"]["min"] for b in buckets[1:]]
        return lo, hi, points

    def get_read_tasks(self, parallelism: int):
        src = self
        pipeline = self.pipeline

        def read_range(flt: Optional[Dict]):
            coll = src._collection()
            if pipeline:
                docs = list(coll.aggregate(pipeline))
            else:
                docs = list(coll.find(flt or {}))
            return _docs_to_block(docs)

        if parallelism <= 1 or pipeline:
            # a user aggregation pipeline ($group/$sort/$limit) computes a
            # GLOBAL answer: sharding it by _id slices would return per-
            # partition partials — run it as one whole-collection task
            return [lambda: read_range(None)]
        try:
            lo, hi, points = self._split_bounds(parallelism)
        except ImportError as e:
            # gated: keep the task-shape contract (N tasks) so pipelines
            # compose; each raises the clear ImportError at execution.
            # The closures must RAISE, not fall back to whole-collection
            # reads — if workers' runtime_env has pymongo while the driver
            # doesn't, N whole-collection closures would duplicate every
            # document N times.
            msg = (f"MongoDatasource requires pymongo on the driver to "
                   f"partition reads: {e}")

            def gated() -> None:
                raise ImportError(msg)

            return [gated for _ in range(parallelism)]
        if lo is None or not points:
            return [lambda: read_range(None)]
        filters = _mongo_range_filters(points, lo, hi)
        return [lambda f=f: read_range(f) for f in filters]


class BigQueryDatasource(Datasource):
    """BigQuery reads partitioned by Storage-API read streams
    (reference: python/ray/data/datasource/bigquery_datasource.py —
    create_read_session(max_stream_count=parallelism), one task per
    stream). Gated like Mongo: composes offline, raises a clear
    ImportError at read time; ``_client_factory`` injects a fake
    storage client so the stream-split path executes in tests."""

    name = "BigQuery"

    def __init__(self, project_id: str, query: Optional[str] = None,
                 dataset: Optional[str] = None, _client_factory=None):
        if not (query or dataset):
            raise ValueError("BigQueryDatasource needs query= or dataset=")
        self.project_id = project_id
        self.query = query
        self.dataset = dataset
        self._client_factory = _client_factory

    def _storage_client(self):
        if self._client_factory is not None:
            return self._client_factory()
        try:
            from google.cloud import bigquery_storage
        except ImportError as e:
            raise ImportError(
                "read_bigquery requires `google-cloud-bigquery[-storage]`"
                ", which is not installed in this environment") from e
        return bigquery_storage.BigQueryReadClient()

    def get_read_tasks(self, parallelism: int):
        src = self

        def read_query():
            # query path: BQ materializes the result; stream-splitting
            # applies to table reads below
            try:
                from google.cloud import bigquery
            except ImportError as e:
                raise ImportError(
                    "read_bigquery requires `google-cloud-bigquery`, "
                    "which is not installed in this environment") from e
            client = bigquery.Client(project=src.project_id)
            return client.query(src.query).to_arrow()

        if self.query:
            return [read_query]

        def read_stream(stream_name: str):
            # client built INSIDE the task: read tasks ship to workers by
            # pickle and a live gRPC client cannot ride the closure
            client = src._storage_client()
            rows = client.read_rows(stream_name)
            if hasattr(rows, "pages"):
                import pyarrow as pa  # bigquery ships arrow batches

                return pa.Table.from_batches(
                    [page.to_arrow() for page in rows.pages])
            return rows.to_arrow()

        # table path: one read task per storage stream
        parts = self.dataset.split(".")
        if len(parts) != 2:
            raise ValueError(
                f"dataset must be '<dataset>.<table>', got {self.dataset!r}")
        table = (f"projects/{self.project_id}/datasets/{parts[0]}"
                 f"/tables/{parts[1]}")
        try:
            client = self._storage_client()
            session = client.create_read_session(
                parent=f"projects/{self.project_id}",
                read_session={"table": table, "data_format": "ARROW"},
                max_stream_count=max(1, parallelism))
            streams = [s.name for s in session.streams]
        except ImportError:
            def gated():
                src._storage_client()  # raises the clear ImportError

            return [gated]
        if not streams:
            return [lambda: {"_empty": []}]
        return [lambda s=s: read_stream(s) for s in streams]
