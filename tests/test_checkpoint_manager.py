"""CheckpointManager retention + BackendExecutor rank-assignment units
(ISSUE 20 satellite: the keep-K pruning and rank logic had no direct
coverage — both were only exercised incidentally through full trainer
runs)."""

import os

import pytest

from ray_tpu.air.config import CheckpointConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.backend_executor import BackendExecutor
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager


def _ckpt(tmp_path, name, payload=b"x"):
    d = tmp_path / name
    d.mkdir()
    (d / "state.bin").write_bytes(payload)
    return Checkpoint(str(d))


# --------------------------------------------------------------- disk keep-K
def test_keep_k_prunes_oldest_without_score(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(num_to_keep=2))
    for i in range(5):
        mgr.register_checkpoint(_ckpt(tmp_path, f"c{i}"), {"loss": float(i)})
    kept = [t.checkpoint.path for t in mgr._checkpoints]
    assert len(kept) == 2
    # recency order: the two newest survive, the three oldest are deleted
    assert kept == [str(tmp_path / "c3"), str(tmp_path / "c4")] or \
        set(kept) == {str(tmp_path / "c3"), str(tmp_path / "c4")}
    for i in range(3):
        assert not os.path.exists(tmp_path / f"c{i}")
    assert os.path.exists(tmp_path / "c3") and os.path.exists(tmp_path / "c4")


def test_keep_k_scored_max_keeps_best(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=2, checkpoint_score_attribute="acc",
        checkpoint_score_order="max"))
    accs = [0.1, 0.9, 0.5, 0.3]
    for i, a in enumerate(accs):
        mgr.register_checkpoint(_ckpt(tmp_path, f"c{i}"), {"acc": a})
    surviving = {t.metrics["acc"] for t in mgr._checkpoints}
    assert surviving == {0.9, 0.5}
    assert not os.path.exists(tmp_path / "c0")
    assert not os.path.exists(tmp_path / "c3")


def test_keep_k_scored_min_keeps_lowest(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=2, checkpoint_score_attribute="loss",
        checkpoint_score_order="min"))
    for i, l in enumerate([5.0, 1.0, 3.0, 0.5]):
        mgr.register_checkpoint(_ckpt(tmp_path, f"c{i}"), {"loss": l})
    surviving = {t.metrics["loss"] for t in mgr._checkpoints}
    assert surviving == {1.0, 0.5}


def test_missing_score_attribute_ranks_worst(tmp_path):
    """A checkpoint without the score attribute must be pruned before any
    scored one, in both orders — min-order must not crown it via the
    sign flip."""
    for order in ("max", "min"):
        mgr = CheckpointManager(CheckpointConfig(
            num_to_keep=1, checkpoint_score_attribute="acc",
            checkpoint_score_order=order))
        mgr.register_checkpoint(
            _ckpt(tmp_path, f"scored_{order}"), {"acc": 0.5})
        mgr.register_checkpoint(_ckpt(tmp_path, f"unscored_{order}"), {})
        assert [t.metrics for t in mgr._checkpoints] == [{"acc": 0.5}]


def test_latest_checkpoint_tracks_registration_order(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(
        num_to_keep=2, checkpoint_score_attribute="acc"))
    mgr.register_checkpoint(_ckpt(tmp_path, "c0"), {"acc": 0.9})
    mgr.register_checkpoint(_ckpt(tmp_path, "c1"), {"acc": 0.1})
    # best is c0, latest is c1; both survive under keep-2
    assert mgr.latest_checkpoint.path == str(tmp_path / "c1")
    assert mgr.best_checkpoint.path == str(tmp_path / "c0")


def test_best_checkpoints_returns_metrics_in_order(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(num_to_keep=3))
    for i in range(3):
        mgr.register_checkpoint(_ckpt(tmp_path, f"c{i}"), {"i": i})
    pairs = mgr.best_checkpoints()
    assert [m["i"] for _, m in pairs] == [0, 1, 2]


def test_score_order_validation():
    with pytest.raises(ValueError):
        CheckpointConfig(checkpoint_score_order="median")


# ----------------------------------------------------- in-store manifests
def test_in_store_retention_and_release(ray_start_regular, monkeypatch):
    import numpy as np

    import ray_tpu
    from ray_tpu._private.config import CONFIG

    monkeypatch.setattr(CONFIG, "train_in_store_keep", 2)
    mgr = CheckpointManager(CheckpointConfig())
    for step in range(4):
        shards = {r: ray_tpu.put(np.full(64, step, np.uint8))
                  for r in range(2)}
        assert mgr.register_in_store(step, shards, {"step": step})
    # keep-2: only the two newest manifests survive
    assert [m.step for m in mgr._in_store] == [2, 3]
    assert mgr.latest_in_store_step == 3
    wire = mgr.latest_in_store_manifest()
    assert wire["step"] == 3 and wire["world_size"] == 2
    # the driver re-owned the shards: reading them back works even though
    # the originals' refs are long out of scope
    for r in range(2):
        assert bytes(ray_tpu.get(wire["shards"][r]))[:1] == b"\x03"
    mgr.release_in_store()
    assert mgr.latest_in_store_manifest() is None
    assert mgr.latest_in_store_step is None


def test_in_store_lost_owner_abandons_step(ray_start_regular):
    """A shard whose owner died between report and re-own must not wedge
    registration: the step is abandoned, the previous manifest stays."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_ref import ObjectRef

    mgr = CheckpointManager(CheckpointConfig())
    good = {0: ray_tpu.put(np.zeros(8, np.uint8))}
    assert mgr.register_in_store(1, good, {})
    # a ref that resolves nowhere (synthetic id, no owner)
    dead = ObjectRef(ObjectID(os.urandom(ObjectID.SIZE)))
    assert not mgr.register_in_store(
        2, {0: ray_tpu.put(np.ones(8, np.uint8)), 1: dead}, {})
    assert mgr.latest_in_store_step == 1


# ------------------------------------------------------- rank assignment
def _meta(node):
    return {"node_id": node, "hostname": node, "accelerators": {}}


def test_assign_ranks_single_node():
    ranks = BackendExecutor.assign_ranks([_meta("a")] * 3)
    assert [r["world_rank"] for r in ranks] == [0, 1, 2]
    assert [r["local_rank"] for r in ranks] == [0, 1, 2]
    assert all(r["node_rank"] == 0 for r in ranks)
    assert all(r["local_world_size"] == 3 for r in ranks)


def test_assign_ranks_multi_node_grouping():
    metas = [_meta("a"), _meta("b"), _meta("a"), _meta("b"), _meta("b")]
    ranks = BackendExecutor.assign_ranks(metas)
    assert [r["world_rank"] for r in ranks] == [0, 1, 2, 3, 4]
    assert [r["local_rank"] for r in ranks] == [0, 0, 1, 1, 2]
    # node_rank by first-seen order
    assert [r["node_rank"] for r in ranks] == [0, 1, 0, 1, 1]
    assert [r["local_world_size"] for r in ranks] == [2, 3, 2, 3, 3]


def test_assign_ranks_empty():
    assert BackendExecutor.assign_ranks([]) == []
