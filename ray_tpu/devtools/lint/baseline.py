"""Baseline manager: grandfathered violations that may only shrink.

The baseline is a checked-in JSON file mapping violation keys (see
``Violation.key()`` — line-number free, so unrelated edits don't churn
it) to occurrence counts. Semantics:

- A violation whose key still has baseline budget is *grandfathered*:
  reported, but not failing. New code can never add to the file except
  via an explicit ``--update-baseline`` (which a reviewer sees as a
  diff growing the file — the thing the tier-1 test forbids).
- A baseline entry matching nothing is *stale*: the violation was fixed
  but the entry lingers. ``--strict-baseline`` (used by the tier-1
  test) fails the run until ``--update-baseline`` shrinks the file, so
  the baseline monotonically ratchets toward empty.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from .model import Violation


def load(path: str) -> Dict[str, int]:
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", data) if isinstance(data, dict) else {}
    return {str(k): int(v) for k, v in entries.items()}


def save(path: str, entries: Dict[str, int]) -> None:
    payload = {
        "comment": ("raylint grandfathered violations — this file may "
                    "only shrink; regenerate with `python -m "
                    "ray_tpu.devtools.lint ray_tpu --update-baseline`"),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def split(violations: List[Violation], baseline: Dict[str, int]
          ) -> Tuple[List[Violation], List[Violation], List[str]]:
    """(failing, grandfathered, stale_keys)."""
    budget = dict(baseline)
    failing: List[Violation] = []
    grandfathered: List[Violation] = []
    for v in violations:
        k = v.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            grandfathered.append(v)
        else:
            failing.append(v)
    stale = [k for k, n in budget.items() if n > 0]
    return failing, grandfathered, stale


def counts(violations: List[Violation]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for v in violations:
        out[v.key()] = out.get(v.key(), 0) + 1
    return out
