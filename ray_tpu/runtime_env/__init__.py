"""Runtime environments (reference: python/ray/_private/runtime_env/ — the
per-node agent runtime_env_agent.py:161, plugin ABC plugin.py:24, URI cache
uri_cache.py, and the RuntimeEnv schema python/ray/runtime_env/runtime_env.py).

Supported fields, applied in the worker process right before it first
executes a task carrying the env (lease scheduling keys already isolate
workers per runtime_env — task_spec.py lease_key — so application happens
exactly once per leased worker):

- ``env_vars``:    {str: str} exported into the worker's os.environ
- ``working_dir``: a local directory, staged into a content-addressed cache
                   under the session dir and chdir'd into
- ``py_modules``:  list of local dirs/py files prepended to sys.path
- ``pip``:         per-env virtualenv with content-addressed caching (in
                   this zero-egress image, requirements must resolve
                   offline or already be importable system-wide)
- ``conda``:       env name/prefix or environment.yml dict; SPAWN-TIME —
                   the agent launches the worker under the env's python
                   (runtime_env/conda.py; reference conda.py:259)
- ``container``:   image spec; SPAWN-TIME — the agent wraps the worker
                   launch in podman/docker (runtime_env/container.py)
- ``config``:      {"setup_timeout_seconds": ...} accepted for parity

TPU-first deviation: no separate per-node HTTP agent process — env setup is
a pure-local operation (tmpfs staging + process env), so it runs in-worker,
keeping the hot lease path free of an extra RPC.
"""

from ray_tpu.runtime_env.runtime_env import (
    RuntimeEnv,
    RuntimeEnvConfig,
    RuntimeEnvSetupError,
)
from ray_tpu.runtime_env.context import RuntimeEnvContext, setup_runtime_env
from ray_tpu.runtime_env.plugin import RuntimeEnvPlugin, register_plugin
import ray_tpu.runtime_env.container  # noqa: F401  (registers the plugin)
import ray_tpu.runtime_env.conda  # noqa: F401  (registers the plugin)

__all__ = [
    "RuntimeEnv",
    "RuntimeEnvConfig",
    "RuntimeEnvSetupError",
    "RuntimeEnvContext",
    "RuntimeEnvPlugin",
    "register_plugin",
    "setup_runtime_env",
]
