"""Autoscaler v2 SDK (reference: python/ray/autoscaler/v2/sdk.py —
get_cluster_status returning the typed ClusterStatus the dashboard and
`ray status` render)."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import ray_tpu


@dataclasses.dataclass
class NodeState:
    node_id: str
    alive: bool
    resources_total: Dict[str, float]
    resources_available: Dict[str, float]
    labels: Dict[str, str]


@dataclasses.dataclass
class ClusterStatus:
    nodes: List[NodeState]
    pending_demand: List[Dict]
    total_resources: Dict[str, float]
    available_resources: Dict[str, float]

    def active_nodes(self) -> List[NodeState]:
        return [n for n in self.nodes if n.alive]


def get_cluster_status() -> ClusterStatus:
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() first")
    nodes_raw = w._acall(w.head.call("ListNodes", {}), timeout=30)
    view = w._acall(w.head.call("GetClusterView", {}), timeout=30)
    nodes = [NodeState(
        node_id=n["node_id"], alive=n["alive"],
        resources_total=n["resources_total"],
        resources_available=n["resources_available"],
        labels=n.get("labels", {})) for n in nodes_raw]
    pending: List[Dict] = []
    for info in view.values():
        pending.extend(info.get("pending", []))
    return ClusterStatus(
        nodes=nodes,
        pending_demand=pending,
        total_resources=ray_tpu.cluster_resources(),
        available_resources=ray_tpu.available_resources(),
    )
