"""R13 — loop/thread/GC affinity races on plain attribute mutation.

Invariant: a ``self.<attr>`` that is plainly mutated (no lock held in
scope) must be confined to ONE affinity domain — event-loop callbacks,
executor/drainer threads, or GC-context finalizers. The same attribute
mutated from two domains with no hand-off is a data race the GIL merely
makes *rare*, not safe.

Motivating shape (PR 18): ``_completion_buf``/``_completions_armed``
are touched by ``_completion_enqueue`` (scheduled onto the loop) and
read-modify-written by ``_drain_completions``; PR 12's shm feeder thread
had the same pattern against the loop. Both had to get the hand-off
right *by hand* — this rule pins the discipline down.

Detection: every function gets a domain set walked to fixpoint from
roots — ``async def`` bodies and ``call_soon*``/``create_task``
callbacks are loop-affine, ``threading.Thread``/``run_in_executor``
targets are thread-affine, ``__del__``/weakref callbacks are GC-affine;
nested defs inherit their enclosing frame's domains. If the union of
domains over all mutation sites of one ``(class, attr)`` spans ≥2
domains, each *unguarded* classified site is flagged. Mutations inside
``__init__`` (construction happens-before publication) and sites under
any held lock are exempt.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .. import concurrency
from ..callgraph import ProjectIndex
from ..model import Violation

RULE_ID = "R13"
SUMMARY = ("same self.<attr> plainly mutated from two thread-affinity "
           "domains (loop/executor/GC) with no lock or queue hand-off "
           "in scope — cross-thread data race")

_CTOR_NAMES = {"__init__", "__new__", "__post_init__",
               "__init_subclass__", "__set_name__"}


def _in_ctor(conc: "concurrency.Concurrency",
             fn: "concurrency.FnNode") -> bool:
    cur = fn
    while cur is not None:
        if cur.info.name in _CTOR_NAMES:
            return True
        cur = conc.fns.get(cur.parent_ref) if cur.parent_ref else None
    return False


def check(index: ProjectIndex) -> List[Violation]:
    conc = concurrency.get(index)
    groups: Dict[Tuple[str, str], List[Tuple]] = {}
    for ref in sorted(conc.fns):
        fn = conc.fns[ref]
        cls = fn.info.class_name
        if not cls or _in_ctor(conc, fn):
            continue
        doms = frozenset(conc.domains.get(ref, ()))
        for attr, node, held in fn.self_writes:
            groups.setdefault((cls, attr), []).append(
                (fn, node, held, doms))

    out: List[Violation] = []
    for (cls, attr) in sorted(groups):
        writes = groups[(cls, attr)]
        all_doms = set()
        for _fn, _node, _held, doms in writes:
            all_doms |= doms
        if len(all_doms) < 2:
            continue
        seen_lines = set()
        for fn, node, held, doms in writes:
            if held or not doms:
                continue  # lock hand-off in scope / unclassified frame
            line_key = (fn.info.module.relpath,
                        getattr(node, "lineno", 0))
            if line_key in seen_lines:
                continue
            seen_lines.add(line_key)
            other = next(
                ((f, n, d) for f, n, _h, d in writes
                 if d - doms), None)
            if other is not None:
                of, onode, od = other
                other_txt = (
                    f"and from {sorted(od)} context at "
                    f"{of.info.module.relpath}:"
                    f"{getattr(onode, 'lineno', 0)} in "
                    f"'{of.info.qualname}'")
            else:
                other_txt = (f"and this frame itself runs in all of "
                             f"{sorted(all_doms)}")
            out.append(fn.info.module.violation(
                RULE_ID, node,
                f"'self.{attr}' of {cls} is mutated from "
                f"{sorted(doms)} context here {other_txt} with no "
                f"lock/queue hand-off in scope — plain cross-domain "
                f"mutation races; guard it, confine it to one domain, "
                f"or annotate the happens-before argument"))
    return out
