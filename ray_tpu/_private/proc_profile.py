"""Env-gated cProfile for the control-plane daemons.

``RAY_TPU_PROFILE_DIR=<dir>`` makes the head and agent profile their
entire lifetime and dump ``<name>-<pid>.pstats`` on clean shutdown
(SIGTERM). This is the instrument behind the multi-client loop analysis
(PROFILE_MULTICLIENT.md): where do the head/agent asyncio loops spend
time while 4 clients submit task batches (reference analog: the asio
event-stats instrumentation, src/ray/common/asio + debug_state dumps).
"""

from __future__ import annotations

import os
from typing import Optional


def maybe_start() -> Optional[object]:
    prof_dir = os.environ.get("RAY_TPU_PROFILE_DIR")
    if not prof_dir:
        return None
    import cProfile

    prof = cProfile.Profile()
    prof.enable()
    return prof


def dump(prof: Optional[object], name: str) -> None:
    if prof is None:
        return
    prof_dir = os.environ.get("RAY_TPU_PROFILE_DIR")
    if not prof_dir:
        return
    try:
        prof.disable()
        os.makedirs(prof_dir, exist_ok=True)
        prof.dump_stats(
            os.path.join(prof_dir, f"{name}-{os.getpid()}.pstats"))
    except Exception:
        pass
