"""Resource algebra for scheduling.

Behavioral parity with the reference's resource model (reference:
``src/ray/common/scheduling/resource_set.h``,
``cluster_resource_data.h``, ``fixed_point.h``): resource amounts are
fixed-point integers (1/10000 granularity) so fractional CPUs/TPUs compare
exactly; a node advertises *total* and *available* sets; requests subtract and
add back atomically. TPU is a predefined resource alongside CPU/GPU/memory —
the TPU-first deviation from the reference, where TPU rode the custom-resource
path (reference: ``python/ray/_private/accelerators/tpu.py:335-398``).
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional

GRANULARITY = 10_000

CPU = "CPU"
GPU = "GPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

PREDEFINED = (CPU, GPU, TPU, MEMORY, OBJECT_STORE_MEMORY)

# Resources that are "unit" resources: requests must map to whole device
# instances when being assigned ids (CPU may be fractional for scheduling but
# accelerators are assigned as whole chips unless the request is < 1).
UNIT_INSTANCE_RESOURCES = (GPU, TPU)


def _to_fixed(value: float) -> int:
    return round(value * GRANULARITY)


def _from_fixed(value: int) -> float:
    return value / GRANULARITY


class ResourceSet:
    """A bag of named resource quantities with fixed-point arithmetic."""

    __slots__ = ("_amounts",)

    def __init__(self, amounts: Optional[Mapping[str, float]] = None):
        self._amounts: Dict[str, int] = {}
        if amounts:
            for name, qty in amounts.items():
                fp = _to_fixed(qty)
                if fp != 0:
                    self._amounts[name] = fp

    @classmethod
    def _from_fixed_map(cls, amounts: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._amounts = {k: v for k, v in amounts.items() if v != 0}
        return rs

    def get(self, name: str) -> float:
        return _from_fixed(self._amounts.get(name, 0))

    def has(self, name: str) -> bool:
        return self._amounts.get(name, 0) > 0

    def names(self) -> Iterable[str]:
        return self._amounts.keys()

    def is_empty(self) -> bool:
        return not self._amounts

    def to_dict(self) -> Dict[str, float]:
        return {k: _from_fixed(v) for k, v in self._amounts.items()}

    def copy(self) -> "ResourceSet":
        return ResourceSet._from_fixed_map(dict(self._amounts))

    # -- algebra -------------------------------------------------------------
    def fits(self, available: "ResourceSet") -> bool:
        """True if `available` can satisfy this request."""
        for name, qty in self._amounts.items():
            if qty > 0 and available._amounts.get(name, 0) < qty:
                return False
        return True

    def feasible_on(self, total: "ResourceSet") -> bool:
        """True if a node with `total` resources could *ever* run this."""
        return self.fits(total)

    def add(self, other: "ResourceSet") -> None:
        for name, qty in other._amounts.items():
            self._amounts[name] = self._amounts.get(name, 0) + qty
            if self._amounts[name] == 0:
                del self._amounts[name]

    def subtract(self, other: "ResourceSet", allow_negative: bool = False) -> bool:
        """Subtract in place. Returns False (and leaves self unchanged) if it
        would go negative and allow_negative is False."""
        if not allow_negative and not other.fits(self):
            return False
        for name, qty in other._amounts.items():
            self._amounts[name] = self._amounts.get(name, 0) - qty
            if self._amounts[name] == 0:
                del self._amounts[name]
        return True

    def __eq__(self, other) -> bool:
        return isinstance(other, ResourceSet) and self._amounts == other._amounts

    def __repr__(self) -> str:
        return f"ResourceSet({self.to_dict()})"

    # -- (de)serialization ---------------------------------------------------
    def to_wire(self) -> Dict[str, int]:
        return dict(self._amounts)

    @classmethod
    def from_wire(cls, wire: Dict[str, int]) -> "ResourceSet":
        return cls._from_fixed_map(dict(wire))


def normalize_label_constraints(d) -> Dict[str, Dict]:
    """Normalize user label constraints into wire form.

    Accepts values that are a string, a list of strings, or the
    In/NotIn/Exists/DoesNotExist helper objects
    (ray_tpu.util.scheduling_strategies); emits
    ``{key: {"op": ..., "values": [...]}}``.
    """
    out: Dict[str, Dict] = {}
    for k, v in (d or {}).items():
        tname = type(v).__name__
        if isinstance(v, str):
            out[k] = {"op": "in", "values": [v]}
        elif tname == "In":
            out[k] = {"op": "in", "values": list(v.values)}
        elif tname == "NotIn":
            out[k] = {"op": "not_in", "values": list(v.values)}
        elif tname == "Exists":
            out[k] = {"op": "exists", "values": []}
        elif tname == "DoesNotExist":
            out[k] = {"op": "not_exists", "values": []}
        else:
            out[k] = {"op": "in", "values": list(v)}
    return out


def label_constraints_match(labels: Mapping[str, str], constraints) -> bool:
    """Evaluate wire-form label constraints against a node's labels."""
    for key, c in (constraints or {}).items():
        op, values = c.get("op", "in"), c.get("values", [])
        present = key in labels
        if op == "in":
            if labels.get(key) not in values:
                return False
        elif op == "not_in":
            if present and labels[key] in values:
                return False
        elif op == "exists":
            if not present:
                return False
        elif op == "not_exists":
            if present:
                return False
    return True


class NodeResources:
    """Total + available resources of one node, plus labels.

    Parity with reference ``cluster_resource_data.h:289`` (NodeResources with
    total/available/labels) in a single class; per-instance accounting for
    accelerator chip ids lives here too (reference: resource_instance_set.h).
    """

    def __init__(
        self,
        total: ResourceSet,
        labels: Optional[Dict[str, str]] = None,
        accelerator_ids: Optional[Dict[str, list]] = None,
    ):
        self.total = total.copy()
        self.available = total.copy()
        self.labels = dict(labels or {})
        # resource name -> list of free device indices, e.g. {"TPU": [0,1,2,3]}
        self.free_instances: Dict[str, list] = {
            k: list(v) for k, v in (accelerator_ids or {}).items()
        }
        self.assigned_instances: Dict[str, Dict[str, list]] = {}  # owner -> name -> ids

    def utilization(self) -> float:
        """Critical-resource utilization in [0,1] — drives the hybrid policy."""
        worst = 0.0
        for name, total_fp in self.total.to_wire().items():
            if total_fp <= 0:
                continue
            avail_fp = self.available.to_wire().get(name, 0)
            worst = max(worst, 1.0 - avail_fp / total_fp)
        return worst

    def allocate(self, request: ResourceSet, owner: str = "") -> Optional[Dict[str, list]]:
        """Try to allocate; returns {resource: [instance ids]} for unit
        resources (empty lists for non-instance resources) or None."""
        if not request.fits(self.available):
            return None
        self.available.subtract(request)
        assigned: Dict[str, list] = {}
        for name in request.names():
            qty = request.get(name)
            if name in self.free_instances and qty >= 1:
                n = int(qty)
                ids = self.free_instances[name][:n]
                self.free_instances[name] = self.free_instances[name][n:]
                assigned[name] = ids
        if owner:
            self.assigned_instances.setdefault(owner, {})
            for name, ids in assigned.items():
                self.assigned_instances[owner].setdefault(name, []).extend(ids)
        return assigned

    def release(self, request: ResourceSet, owner: str = "") -> None:
        self.available.add(request)
        # Clamp: never exceed total (defensive against double-release).
        for name, total_fp in self.total.to_wire().items():
            avail = self.available.to_wire().get(name, 0)
            if avail > total_fp:
                self.available = ResourceSet._from_fixed_map(
                    {**self.available.to_wire(), name: total_fp}
                )
        if owner and owner in self.assigned_instances:
            for name, ids in self.assigned_instances.pop(owner).items():
                self.free_instances.setdefault(name, []).extend(sorted(ids))

    def to_wire(self) -> Dict:
        return {
            "total": self.total.to_wire(),
            "available": self.available.to_wire(),
            "labels": self.labels,
            "free_instances": self.free_instances,
        }

    @classmethod
    def from_wire(cls, wire: Dict) -> "NodeResources":
        nr = cls(ResourceSet.from_wire(wire["total"]), wire.get("labels"))
        nr.available = ResourceSet.from_wire(wire["available"])
        nr.free_instances = {k: list(v) for k, v in wire.get("free_instances", {}).items()}
        return nr
