"""Trial callbacks + result loggers (reference: python/ray/tune/logger/ —
CSVLoggerCallback, JsonLoggerCallback, TBXLoggerCallback — and
python/ray/tune/callback.py Callback hooks).

Per-trial files land in the trial's local dir: ``progress.csv``,
``result.json`` (one JSON object per line), and TensorBoard event files
when ``tensorboardX``/torch tensorboard is importable (gated — not in
this image's baked set).
"""

from __future__ import annotations

import csv
import json
import numbers
import os
from typing import Dict, List, Optional, TextIO


class Callback:
    """Experiment-loop hooks (reference: tune/callback.py Callback)."""

    def on_trial_start(self, iteration: int, trials: List, trial) -> None:
        pass

    def on_trial_result(self, iteration: int, trials: List, trial,
                        result: Dict) -> None:
        pass

    def on_trial_complete(self, iteration: int, trials: List,
                          trial) -> None:
        pass

    def on_trial_error(self, iteration: int, trials: List, trial) -> None:
        pass

    def on_experiment_end(self, trials: List) -> None:
        pass


class LoggerCallback(Callback):
    """Base for per-trial file loggers."""

    def log_trial_start(self, trial) -> None:
        pass

    def log_trial_result(self, trial, result: Dict) -> None:
        pass

    def log_trial_end(self, trial) -> None:
        pass

    def on_trial_start(self, iteration, trials, trial) -> None:
        self.log_trial_start(trial)

    def on_trial_result(self, iteration, trials, trial, result) -> None:
        self.log_trial_result(trial, result)

    def on_trial_complete(self, iteration, trials, trial) -> None:
        self.log_trial_end(trial)

    def on_trial_error(self, iteration, trials, trial) -> None:
        # errored trials must still close/flush their files
        self.log_trial_end(trial)


def _flatten(d: Dict, prefix: str = "") -> Dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


class JsonLoggerCallback(LoggerCallback):
    """One JSON object per result line in ``result.json`` (+ params.json),
    the format ``tune.ExperimentAnalysis``/ResultGrid re-read."""

    def __init__(self):
        self._files: Dict[str, TextIO] = {}

    def log_trial_start(self, trial) -> None:
        os.makedirs(trial.local_dir, exist_ok=True)
        with open(os.path.join(trial.local_dir, "params.json"), "w") as f:
            json.dump(trial.config, f, default=str)

    def _fh(self, trial) -> TextIO:
        if trial.trial_id not in self._files:
            os.makedirs(trial.local_dir, exist_ok=True)
            self._files[trial.trial_id] = open(
                os.path.join(trial.local_dir, "result.json"), "a")
        return self._files[trial.trial_id]

    def log_trial_result(self, trial, result: Dict) -> None:
        fh = self._fh(trial)
        fh.write(json.dumps(result, default=str) + "\n")
        fh.flush()

    def log_trial_end(self, trial) -> None:
        fh = self._files.pop(trial.trial_id, None)
        if fh:
            fh.close()


class CSVLoggerCallback(LoggerCallback):
    """``progress.csv`` with a header from the first result's flat keys."""

    def __init__(self):
        self._writers: Dict[str, csv.DictWriter] = {}
        self._files: Dict[str, TextIO] = {}

    def log_trial_result(self, trial, result: Dict) -> None:
        flat = _flatten(result)
        if trial.trial_id not in self._writers:
            os.makedirs(trial.local_dir, exist_ok=True)
            fh = open(os.path.join(trial.local_dir, "progress.csv"), "a")
            self._files[trial.trial_id] = fh
            writer = csv.DictWriter(fh, fieldnames=sorted(flat.keys()),
                                    extrasaction="ignore")
            writer.writeheader()
            self._writers[trial.trial_id] = writer
        self._writers[trial.trial_id].writerow(
            {k: v for k, v in flat.items()})
        self._files[trial.trial_id].flush()

    def log_trial_end(self, trial) -> None:
        fh = self._files.pop(trial.trial_id, None)
        self._writers.pop(trial.trial_id, None)
        if fh:
            fh.close()


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard scalars via tensorboardX (or torch.utils.tensorboard).
    Gated: raises a clear ImportError if neither backend is available."""

    def __init__(self):
        self._writer_cls = None
        try:
            from tensorboardX import SummaryWriter  # type: ignore
            self._writer_cls = SummaryWriter
        except ImportError:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._writer_cls = SummaryWriter
            except ImportError:
                raise ImportError(
                    "TBXLoggerCallback needs `tensorboardX` or torch's "
                    "tensorboard; neither is installed. Use "
                    "CSVLoggerCallback/JsonLoggerCallback instead.")
        self._writers: Dict[str, object] = {}

    def log_trial_result(self, trial, result: Dict) -> None:
        if trial.trial_id not in self._writers:
            self._writers[trial.trial_id] = self._writer_cls(
                logdir=trial.local_dir)
        w = self._writers[trial.trial_id]
        step = result.get("training_iteration", 0)
        for k, v in _flatten(result).items():
            if isinstance(v, numbers.Number):
                w.add_scalar(k, v, global_step=step)
        w.flush()

    def log_trial_end(self, trial) -> None:
        w = self._writers.pop(trial.trial_id, None)
        if w:
            w.close()


DEFAULT_LOGGERS = (JsonLoggerCallback, CSVLoggerCallback)
