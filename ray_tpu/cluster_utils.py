"""In-process multi-node test cluster.

Parity with the reference's test fixture (reference:
``python/ray/cluster_utils.py:108``): boots a head plus any number of
additional node agents as separate local processes sharing one session, so
multi-node scheduling, spillback, object transfer and failover are testable on
one machine (SURVEY §4 tier-2 strategy).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.node import Node


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self.head_node: Optional[Node] = None
        self.worker_nodes: List[Node] = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.head_node.head_port}"

    @property
    def session_dir(self) -> str:
        return self.head_node.session_dir

    def add_node(self, num_cpus: Optional[int] = None,
                 num_tpus: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None) -> Node:
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            res["TPU"] = float(num_tpus)
        if self.head_node is None:
            node = Node(head=True, resources=res or None, labels=labels,
                        object_store_memory=object_store_memory)
            node.start()
            self.head_node = node
        else:
            node = Node(
                head=False,
                head_host="127.0.0.1",
                head_port=self.head_node.head_port,
                resources=res or None,
                labels=labels,
                object_store_memory=object_store_memory,
                session_dir=self.head_node.session_dir,
            )
            node.start()
            self.worker_nodes.append(node)
        return node

    def remove_node(self, node: Node, allow_graceful: bool = True) -> None:
        if node is self.head_node:
            raise ValueError("use shutdown() to remove the head node")
        node.stop()
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Block until every started node is registered and alive."""
        import ray_tpu

        expected = 1 + len(self.worker_nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                alive = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(alive) >= expected:
                    return
            except Exception:
                pass
            time.sleep(0.1)
        raise TimeoutError(f"cluster did not reach {expected} nodes")

    def shutdown(self) -> None:
        for node in self.worker_nodes:
            node.stop()
        self.worker_nodes.clear()
        if self.head_node is not None:
            self.head_node.stop(cleanup_session=True)
            self.head_node = None


class AutoscalingCluster:
    """Head node + autoscaler monitor over the local node provider
    (reference: ``cluster_utils.py:25`` AutoscalingCluster driving the fake
    multi-node provider). Worker nodes are launched/terminated on demand as
    real local agent processes.
    """

    def __init__(self, head_resources: Optional[Dict[str, float]] = None,
                 worker_node_types: Optional[Dict[str, Dict]] = None,
                 idle_timeout_minutes: float = 0.05,
                 max_workers: int = 8,
                 update_interval_s: float = 0.5,
                 provider_cls=None):
        self._head_resources = head_resources or {"CPU": 2}
        self._worker_node_types = worker_node_types or {}
        self._idle_timeout_minutes = idle_timeout_minutes
        self._max_workers = max_workers
        self._update_interval_s = update_interval_s
        self._provider_cls = provider_cls
        self.cluster: Optional[Cluster] = None
        self.monitor = None
        self.provider = None

    @property
    def address(self) -> str:
        return self.cluster.address

    def start(self) -> None:
        from ray_tpu.autoscaler.monitor import Monitor
        from ray_tpu.autoscaler.node_provider import LocalNodeProvider

        self.cluster = Cluster(
            initialize_head=True,
            head_node_args={"resources": self._head_resources})
        head = self.cluster.head_node
        provider_cls = self._provider_cls or LocalNodeProvider
        self.provider = provider_cls(
            {"head_host": "127.0.0.1", "head_port": head.head_port,
             "session_dir": head.session_dir,
             "node_types": self._worker_node_types},
            cluster_name="autoscaling-test")
        self.monitor = Monitor(
            {"idle_timeout_minutes": self._idle_timeout_minutes,
             "max_workers": self._max_workers,
             "available_node_types": self._worker_node_types},
            self.provider, "127.0.0.1", head.head_port,
            update_interval_s=self._update_interval_s)
        self.monitor.start()

    def shutdown(self) -> None:
        if self.monitor:
            self.monitor.stop()
        if self.provider:
            self.provider.shutdown()
        if self.cluster:
            self.cluster.shutdown()
