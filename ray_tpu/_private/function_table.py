"""Function shipping: descriptors, export and worker-side cache.

Parity with the reference's function manager (reference:
``python/ray/_private/function_manager.py`` + ``GcsFunctionManager``): remote
functions are cloudpickled once, identified by content hash, inlined in the
task spec when small, exported through the head KV when large, and cached by
executing workers.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict, Optional, Tuple

from ray_tpu._private import serialization as ser

INLINE_FUNCTION_MAX = 16 * 1024
_KV_NS = "funcs"

# Cross-language function id: a task spec carrying this sentinel names its
# target by module path in ``function_name`` ("pkg.mod:qualname") instead
# of shipping a pickled blob — non-Python drivers (the C++ client) cannot
# cloudpickle (reference: python/ray/cross_language.py function
# descriptors address Python targets by module/name the same way).
XLANG_PYREF_FID = b"xlang-pyref\x00\x00\x00\x00\x00"
assert len(XLANG_PYREF_FID) == 16


def load_pyref(name: str) -> Callable:
    """Resolve "pkg.mod:qualname" (or dotted fallback) to a callable."""
    import importlib

    if ":" in name:
        module_name, qual = name.split(":", 1)
    else:
        module_name, _, qual = name.rpartition(".")
        if not module_name:
            raise RuntimeError(
                f"cross-language function name {name!r} must be "
                "'module:qualname'")
    module = importlib.import_module(module_name)
    target = module
    for part in qual.split("."):
        target = getattr(target, part)
    if not callable(target):
        raise RuntimeError(f"{name!r} resolved to a non-callable")
    return target

import weakref

_export_lock = threading.Lock()
# Keyed by the function object itself (weakly): an id()-keyed cache would
# alias a new function that reuses a collected function's address.
_descriptor_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_function_cache: Dict[bytes, Callable] = {}


def function_descriptor(function: Callable, worker) -> Tuple[bytes, Optional[bytes], str]:
    """Returns (function_id, inline_blob_or_None, name); exports to KV if big."""
    try:
        cached = _descriptor_cache.get(function)
    except TypeError:
        cached = None
    if cached is not None:
        return cached
    blob = ser.dumps(function)
    fid = hashlib.sha1(blob).digest()[:16]
    name = getattr(function, "__qualname__", getattr(function, "__name__", "fn"))
    if len(blob) <= INLINE_FUNCTION_MAX:
        result = (fid, blob, name)
    else:
        with _export_lock:
            worker.kv().put(fid, blob, overwrite=False, namespace=_KV_NS)
        result = (fid, None, name)
    try:
        _descriptor_cache[function] = result
    except TypeError:
        pass  # non-weakref-able callables are re-pickled each call
    return result


def load_function(fid: bytes, blob: Optional[bytes], worker,
                  name: str = "") -> Callable:
    if fid == XLANG_PYREF_FID:
        fn = _function_cache.get(b"pyref:" + name.encode())
        if fn is None:
            fn = load_pyref(name)
            _function_cache[b"pyref:" + name.encode()] = fn
        return fn
    fn = _function_cache.get(fid)
    if fn is not None:
        return fn
    if blob is None:
        blob = worker.kv().get(fid, namespace=_KV_NS)
        if blob is None:
            raise RuntimeError(f"function {fid.hex()} not found in function table")
    fn = ser.loads(blob)
    _function_cache[fid] = fn
    return fn
