"""LearnerGroup (reference: rllib/core/learner/learner_group.py:71 —
update_from_batch :210).

Two modes:

- **local** (num_learners=0): one in-process Learner whose jitted update is
  sharded over the local device mesh — the default TPU path (GSPMD psum
  over ICI replaces the reference's DDP allreduce).
- **remote** (num_learners=N): N learner actors, decentralized-DP style
  (reference DD-PPO rllib/algorithms/ddppo/ddppo.py:16): each computes
  gradients on its batch shard and allreduces them through
  ``ray_tpu.util.collective`` before applying — params stay bitwise
  identical across learners (deterministic optax), no central parameter
  server.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.learner import Learner, PPOLearner
from ray_tpu.rllib.core.rl_module import RLModuleSpec


class _RemoteLearner:
    """Actor hosting one Learner with DDP gradient sync."""

    def __init__(self, learner_cls, module_spec, config: Dict,
                 group_name: str, rank: int, world_size: int):
        import jax

        self._learner = learner_cls(module_spec, config, use_mesh=False)
        self._group_name = group_name
        self._rank = rank
        self._world = world_size
        self._jax = jax
        if world_size > 1:
            from ray_tpu.util import collective as col

            col.init_collective_group(
                world_size, rank, backend="cpu", group_name=group_name)
            self._col = col
        # gradient-sync update: allreduce grads before apply
        import optax

        def grads_fn(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                self._learner.loss, has_aux=True)(params, batch)
            metrics["total_loss"] = loss
            return grads, metrics

        self._grads_fn = jax.jit(grads_fn)

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self._learner.tx.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._apply_fn = jax.jit(apply_fn)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        lrn = self._learner
        cfg = lrn.config
        num_epochs = cfg.get("num_epochs", 1)
        minibatch = cfg.get("minibatch_size") or len(batch["obs"])
        n = len(batch["obs"])
        rng = np.random.default_rng(cfg.get("seed", 0))
        metrics: Dict[str, Any] = {}
        for _ in range(num_epochs):
            order = rng.permutation(n)
            for s in range(0, n - minibatch + 1, minibatch):
                idx = order[s:s + minibatch]
                mb = {k: v[idx] for k, v in batch.items()}
                grads, metrics = self._grads_fn(lrn.params, mb)
                if self._world > 1:
                    leaves, treedef = jax.tree.flatten(grads)
                    flat = np.concatenate(
                        [np.ravel(np.asarray(g)) for g in leaves])
                    flat = self._col.allreduce(
                        flat, group_name=self._group_name) / self._world
                    out, off = [], 0
                    for g in leaves:
                        size = int(np.prod(np.shape(g)))
                        out.append(flat[off:off + size].reshape(np.shape(g)))
                        off += size
                    grads = jax.tree.unflatten(treedef, out)
                lrn.params, lrn.opt_state = self._apply_fn(
                    lrn.params, lrn.opt_state, grads)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return self._learner.get_weights()

    def set_weights(self, weights):
        self._learner.set_weights(weights)
        return True

    def get_state(self):
        return self._learner.get_state()

    def set_state(self, state):
        self._learner.set_state(state)
        return True

    def stop(self):
        return True


class LearnerGroup:
    def __init__(self, learner_cls, module_spec: RLModuleSpec, config: Dict,
                 num_learners: int = 0,
                 resources_per_learner: Optional[Dict] = None):
        self._num = num_learners
        self._local: Optional[Learner] = None
        self._workers: List = []
        if num_learners == 0:
            self._local = learner_cls(module_spec, config)
        else:
            import uuid

            group = f"learners_{uuid.uuid4().hex[:6]}"
            res = resources_per_learner or {"CPU": 1}
            for rank in range(num_learners):
                self._workers.append(
                    ray_tpu.remote(_RemoteLearner).options(
                        resources=dict(res)).remote(
                            learner_cls, module_spec, config, group,
                            rank, num_learners))

    @property
    def is_local(self) -> bool:
        return self._local is not None

    def local_learner(self) -> Learner:
        """The in-process learner (off-policy algos drive it directly for
        target-net/epsilon state; they require num_learners=0)."""
        if self._local is None:
            raise RuntimeError(
                "this algorithm drives a local learner; configure "
                "num_learners=0")
        return self._local

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch)
        # shard the train batch across learners (equal slices)
        n = len(batch["obs"])
        k = len(self._workers)
        per = n // k
        refs = []
        for i, w in enumerate(self._workers):
            shard = {key: v[i * per:(i + 1) * per] for key, v in batch.items()}
            refs.append(w.update.remote(shard))
        all_metrics = ray_tpu.get(refs, timeout=600)
        return {k2: float(np.mean([m[k2] for m in all_metrics]))
                for k2 in all_metrics[0]}

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._workers[0].get_weights.remote(),
                           timeout=120)

    def set_weights(self, weights) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            ray_tpu.get([w.set_weights.remote(weights)
                         for w in self._workers], timeout=120)

    def get_state(self) -> Dict:
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._workers[0].get_state.remote(), timeout=120)

    def set_state(self, state: Dict) -> None:
        if self._local is not None:
            self._local.set_state(state)
        else:
            ray_tpu.get([w.set_state.remote(state)
                         for w in self._workers], timeout=120)

    def shutdown(self) -> None:
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
