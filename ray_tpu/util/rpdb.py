"""Remote pdb over TCP (reference: python/ray/util/rpdb.py —
ray.util.pdb.set_trace() opening a socket-backed pdb a developer telnets
into; debugpy variant in _private/worker debugger hooks).

``set_trace()`` from inside a task/actor binds a listener on a free port,
announces host:port on stderr (which streams to the driver via the log
monitor), and blocks the worker until a client attaches:

    nc 127.0.0.1 <port>
"""

from __future__ import annotations

import pdb
import socket
import sys


class _SocketIO:
    def __init__(self, conn: socket.socket):
        self._file = conn.makefile("rw", buffering=1)

    def readline(self):
        return self._file.readline()

    def write(self, data):
        self._file.write(data)

    def flush(self):
        self._file.flush()


class RemotePdb(pdb.Pdb):
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(1)
        bound = self._sock.getsockname()
        print(f"RemotePdb session waiting at {bound[0]}:{bound[1]} — "
              f"attach with: nc {bound[0]} {bound[1]}",
              file=sys.stderr, flush=True)
        conn, _ = self._sock.accept()
        self._conn = conn
        io = _SocketIO(conn)
        super().__init__(stdin=io, stdout=io)

    def do_quit(self, arg):
        try:
            self._conn.close()
            self._sock.close()
        except OSError:
            pass
        return super().do_quit(arg)

    do_q = do_exit = do_quit


def set_trace(host: str = "127.0.0.1", port: int = 0) -> None:
    """Breakpoint inside a remote task/actor (reference: ray.util.rpdb
    set_trace)."""
    debugger = RemotePdb(host=host, port=port)
    debugger.set_trace(sys._getframe().f_back)
