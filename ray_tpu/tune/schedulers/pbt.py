"""Population Based Training (reference: python/ray/tune/schedulers/pbt.py:221
PopulationBasedTraining — quantile-based exploit of checkpoints + explore by
hyperparameter perturbation).

The exploit path here returns ``TrialScheduler.RESTART`` after mutating
``trial.config`` and setting ``trial.restore_path`` to the donor's
checkpoint; the controller tears the trial actor down and relaunches it with
the new config from that checkpoint (slice-granular restart reuses the same
machinery as fault recovery).
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Union

from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class PopulationBasedTraining(TrialScheduler):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 time_attr: str = "training_iteration",
                 perturbation_interval: float = 4,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 perturbation_factors=(1.2, 0.8),
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations is required for PBT")
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.time_attr = time_attr
        self.perturbation_interval = perturbation_interval
        self.hyperparam_mutations = hyperparam_mutations
        self.quantile_fraction = quantile_fraction
        self.resample_probability = resample_probability
        self.perturbation_factors = perturbation_factors
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._scores: Dict[str, float] = {}
        self._exploits = 0

    # ------------------------------------------------------------ explore
    def _mutate_value(self, current, spec):
        if isinstance(spec, Domain):
            return spec.sample(self._rng)
        if isinstance(spec, list):
            if self._rng.random() < self.resample_probability or \
                    current not in spec:
                return self._rng.choice(spec)
            # shift to a neighboring value (reference pbt.py explore)
            i = spec.index(current)
            j = min(max(i + self._rng.choice((-1, 1)), 0), len(spec) - 1)
            return spec[j]
        if callable(spec):
            return spec()
        raise TypeError(f"unsupported mutation spec {spec!r}")

    def _explore(self, config: Dict) -> Dict:
        new = dict(config)
        for key, spec in self.hyperparam_mutations.items():
            cur = new.get(key)
            if isinstance(cur, (int, float)) and not isinstance(spec, list) \
                    and self._rng.random() >= self.resample_probability:
                factor = self._rng.choice(self.perturbation_factors)
                new[key] = type(cur)(cur * factor)
            else:
                new[key] = self._mutate_value(cur, spec)
        return new

    # ------------------------------------------------------------- exploit
    def _quantiles(self, controller, trial) -> (List, List):
        trials = [t for t in controller.live_trials()
                  if t.trial_id in self._scores]
        if trial.trial_id in self._scores and trial not in trials:
            trials.append(trial)
        trials.sort(key=lambda t: self._scores[t.trial_id])
        if len(trials) <= 1:
            return [], []
        num = max(1, int(len(trials) * self.quantile_fraction))
        if num > len(trials) / 2:
            num = int(len(trials) / 2)
        return trials[:num], trials[-num:]

    def on_trial_result(self, controller, trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        self._scores[trial.trial_id] = self._score(result)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.perturbation_interval:
            return TrialScheduler.CONTINUE
        self._last_perturb[trial.trial_id] = t

        lower, upper = self._quantiles(controller, trial)
        if trial in lower and upper:
            donor = self._rng.choice(upper)
            ckpt = controller.trial_checkpoint(donor)
            if ckpt is None:
                return TrialScheduler.CONTINUE
            trial.config = self._explore(dict(donor.config))
            trial.restore_path = ckpt
            self._exploits += 1
            return TrialScheduler.RESTART
        # top/middle trials checkpoint at each perturbation interval so they
        # can donate (reference: pbt checkpoints on _save_trial_state)
        controller.request_checkpoint(trial)
        return TrialScheduler.CONTINUE

    def debug_string(self) -> str:
        return f"PBT: {self._exploits} exploits"
