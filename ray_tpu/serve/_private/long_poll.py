"""Long-poll config push (reference: python/ray/serve/_private/long_poll.py
— LongPollHost :175 / LongPollClient :66). Clients block on
``listen_for_change({key: last_snapshot_id})``; the host replies as soon as
any key advances past the client's snapshot."""

from __future__ import annotations

import asyncio
from typing import Any, Dict


class LongPollHost:
    """Mixin for the controller: versioned key→value store with blocking
    listeners."""

    def __init__(self):
        # raylint: disable=R10 -- bounded: one entry per long-poll KEY
        # (route table, per-deployment replica sets) — the key space is
        # the serve config's deployments, not per-request traffic
        self._snapshots: Dict[str, int] = {}
        # raylint: disable=R10 -- bounded: same fixed key space as above
        self._values: Dict[str, Any] = {}
        self._changed = asyncio.Event()

    def notify_changed(self, key: str, value: Any) -> None:
        self._values[key] = value
        self._snapshots[key] = self._snapshots.get(key, 0) + 1
        self._changed.set()

    def get_snapshot(self, key: str):
        return self._snapshots.get(key, 0), self._values.get(key)

    async def listen_for_change(self, keys: Dict[str, int],
                                timeout: float = 30.0) -> Dict[str, Any]:
        """Return {key: (snapshot_id, value)} for keys newer than the
        client's ids; empty dict on timeout."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            updates = {
                k: (self._snapshots.get(k, 0), self._values.get(k))
                for k, sid in keys.items()
                if self._snapshots.get(k, 0) > sid
            }
            if updates:
                return updates
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                return {}
            self._changed.clear()
            try:
                await asyncio.wait_for(self._changed.wait(), remaining)
            except asyncio.TimeoutError:
                return {}
