"""The real multi-host path: separate worker OS processes forming ONE global
jax mesh via ``jax.distributed.initialize`` (VERDICT r3 missing #1).

The reference's equivalent — rendezvous then process-group init across
separate worker processes — is python/ray/train/torch/config.py:47-132;
here the mesh is formed the jax way (coordinator rendezvous + gloo CPU
collectives standing in for ICI, per jax's own multiprocess CPU testing
recipe: each process contributes ``num_local_devices`` devices and
``jax.device_count()`` goes global).

Covered end-to-end:
  * 2 worker processes x 2 local devices -> one 4-device global mesh,
    verified from inside the workers (process_count, device_count) and by a
    cross-process psum whose value only a global mesh can produce.
  * sharded training (data axis spans processes) with per-shard
    checkpoints — each process writes only its addressable shards.
  * kill one worker mid-training -> slice-granular restart re-forms the
    mesh (fresh coordinator port) and resumes from the checkpoint.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.jax import JaxConfig, JaxTrainer


@pytest.fixture(scope="module")
def cluster_2w():
    import ray_tpu as ray

    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield ray
    ray.shutdown()


def _mesh_probe_loop(config):
    """Verify the global mesh from inside a worker, then psum across it."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu import train

    ctx = train.get_context()
    rank = ctx.get_world_rank()
    world = ctx.get_world_size()
    facts = {
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
    n = jax.device_count()
    sh = NamedSharding(mesh, P("data"))
    # each process contributes rows valued rank+1; the global sum is only
    # right if the mesh really spans both processes
    local = np.full((n // world * 1, 4), float(rank + 1), np.float32)
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local)
    total = float(jax.jit(lambda a: a.sum(),
                          out_shardings=NamedSharding(mesh, P()))(x))
    facts["global_sum"] = total
    train.report(facts)


def test_two_process_global_mesh(cluster_2w, tmp_path):
    trainer = JaxTrainer(
        _mesh_probe_loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        jax_config=JaxConfig(use_jax_distributed=True, jax_platform="cpu",
                             num_local_devices=2, cpu_collectives="gloo"),
        run_config=RunConfig(storage_path=str(tmp_path), name="mesh_probe"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    m = result.metrics
    assert m["process_count"] == 2
    assert m["local_devices"] == 2
    assert m["global_devices"] == 4
    # rank0 rows sum 1*2*4=8, rank1 rows 2*2*4=16 -> 24 (wrong mesh gives 8)
    assert m["global_sum"] == pytest.approx(24.0)


def _sharded_train_loop(config):
    """Linear-regression SGD on a mesh spanning both processes, with
    per-shard checkpoints and a one-shot crash to exercise slice-granular
    restart + mesh re-formation."""
    import pickle

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu import train

    ctx = train.get_context()
    rank = ctx.get_world_rank()
    world = ctx.get_world_size()
    assert jax.process_count() == world

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
    repl = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P("data"))

    # fixed synthetic regression problem, identical in every process
    rng = np.random.RandomState(0)
    X_all = rng.randn(16, 8).astype(np.float32)
    w_true = rng.randn(8).astype(np.float32)
    y_all = X_all @ w_true
    per = 16 // world
    X = jax.make_array_from_process_local_data(
        row_sharded, X_all[rank * per:(rank + 1) * per])
    y = jax.make_array_from_process_local_data(
        row_sharded, y_all[rank * per:(rank + 1) * per])

    start_step = 0
    w = jnp.zeros((8,), jnp.float32)
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.path, "state.pkl"), "rb") as f:
            state = pickle.load(f)
        start_step = state["step"] + 1
        w = jnp.asarray(state["w"])
    w = jax.device_put(w, repl)

    @jax.jit
    def step(w, X, y):
        def loss_fn(w):
            pred = X @ w
            return jnp.mean((pred - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.15 * g, loss

    crash_at = config.get("crash_at", -1)
    marker = config["crash_marker"]
    for i in range(start_step, config["steps"]):
        w, loss = step(w, X, y)
        if i == crash_at and rank == 1 and not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)  # simulate a host dying mid-step
        # per-shard checkpoint: every process persists only what it owns
        # (here w is replicated so shards coincide, but X/y rows prove the
        # addressable-shard path); rank 0's dir is canonical
        import tempfile

        d = tempfile.mkdtemp(prefix=f"ckpt_r{rank}_")
        with open(os.path.join(d, "state.pkl"), "wb") as f:
            pickle.dump({
                "step": i,
                "w": np.asarray(jax.device_get(w)),
                "my_rows": np.asarray(
                    X.addressable_shards[0].data)[:1].tolist(),
                "resumed_from": start_step,
            }, f)
        from ray_tpu.train import Checkpoint

        train.report({"step": i, "loss": float(loss),
                      "resumed_from": start_step,
                      "mesh_devices": jax.device_count()},
                     checkpoint=Checkpoint(d))


def test_sharded_train_crash_restart_resume(cluster_2w, tmp_path):
    marker = str(tmp_path / "crashed_once")
    trainer = JaxTrainer(
        _sharded_train_loop,
        train_loop_config={"steps": 40, "crash_at": 15,
                           "crash_marker": marker},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        jax_config=JaxConfig(use_jax_distributed=True, jax_platform="cpu",
                             num_local_devices=2, cpu_collectives="gloo"),
        run_config=RunConfig(storage_path=str(tmp_path), name="crash_resume",
                             failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert os.path.exists(marker), "the crash never fired"
    m = result.metrics
    assert m["step"] == 39  # ran to completion
    assert m["mesh_devices"] == 4  # the re-formed mesh is still global
    # the restart resumed from a checkpoint (>0), not from scratch
    assert m["resumed_from"] > 0
    # loss actually converged across the crash boundary
    assert m["loss"] < 1e-2
    # and the final checkpoint carries the resumed lineage
    import pickle

    with open(os.path.join(result.checkpoint.path, "state.pkl"), "rb") as f:
        state = pickle.load(f)
    assert state["step"] == 39
    err = float(np.abs(np.asarray(state["w"])).max())
    assert np.isfinite(err)
