"""BC — behavior cloning from offline data (reference:
rllib/algorithms/bc/bc.py + bc_torch_learner: supervised
-logp(action|obs) on logged transitions; the entry point of the offline
family MARWIL/CQL/CRR share).

No env runners: the dataset (offline/json_io.py JsonReader) is the sole
experience source; an env is only probed for spaces when obs/action dims
are not given explicitly.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.offline import JsonReader


class BCLearner(Learner):
    def loss(self, params, batch):
        out = self.module.forward(params, batch["obs"])
        logp = self.module.dist.logp(out["logits"], batch["actions"])
        bc_loss = -jnp.mean(logp)
        entropy = jnp.mean(self.module.dist.entropy(out["logits"]))
        return bc_loss, {"bc_loss": bc_loss, "entropy": entropy}


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or BC)
        self.offline_data: Optional[str] = None  # dir or glob of .jsonl
        self.dataset_epochs_per_iter = 1
        self.train_batch_size = 256
        self.num_env_runners = 0  # offline: no rollouts
        self.obs_dim: Optional[int] = None
        self.action_dim: Optional[int] = None
        self.discrete: bool = True

    def _training_keys(self):
        return {"offline_data", "dataset_epochs_per_iter", "obs_dim",
                "action_dim", "discrete"}

    def offline(self, *, offline_data: str) -> "BCConfig":
        self.offline_data = offline_data
        return self

    def module_spec(self) -> RLModuleSpec:
        if self.obs_dim is not None and self.action_dim is not None:
            return RLModuleSpec(
                obs_dim=self.obs_dim, action_dim=self.action_dim,
                discrete=self.discrete,
                hiddens=tuple(self.model.get("hiddens", (64, 64))),
                activation=self.model.get("activation", "tanh"))
        return super().module_spec()  # probe the env for spaces


class BC(Algorithm):
    learner_cls = BCLearner

    @classmethod
    def get_default_config(cls):
        return BCConfig(algo_class=cls)

    def setup(self, _config) -> None:
        cfg = self._algo_config
        if not cfg.offline_data:
            raise ValueError("BC requires config.offline(offline_data=...)")
        # base setup builds module spec + learner group; the env-runner loop
        # is a no-op since BCConfig pins num_env_runners=0
        super().setup(_config)
        self.reader = JsonReader(cfg.offline_data, seed=cfg.seed)

    def training_step(self) -> Dict:
        cfg = self.config
        full = self.reader.concat_all()
        n = len(full["obs"])
        steps = max(1, int(cfg.dataset_epochs_per_iter * n
                           / cfg.train_batch_size))
        metrics: Dict = {}
        for _ in range(steps):
            batch = self.reader.sample(cfg.train_batch_size)
            metrics = self.learner_group.update({
                "obs": batch["obs"].astype(np.float32),
                "actions": batch["actions"],
            })
        metrics["env_steps_this_iter"] = 0
        metrics["dataset_rows"] = n
        return metrics
