"""Pipeline parallelism tests (virtual 8-device CPU mesh).

Reference has no native PP (SURVEY §2.5 — integrations only); these verify
the GPipe microbatch schedule in ray_tpu/parallel/pipeline.py: forward
equivalence to sequential stage application, gradient flow, and DP x PP
composition.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from ray_tpu.parallel.pipeline import (
    init_stage_params, make_pipeline_train_step, num_stages, pipeline_apply)

D = 16


def _init_fn(key):
    k1, k2 = jax.random.split(key)
    return {"w": jax.random.normal(k1, (D, D)) * 0.1,
            "b": jax.random.normal(k2, (D,)) * 0.1}


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _sequential(params, x, n):
    host = jax.device_get(params)
    h = x
    for s in range(n):
        h = _stage_fn(jax.tree.map(lambda a: a[s], host), h)
    return h


@pytest.fixture(scope="module")
def pp_mesh():
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("data", "stage"))


@pytest.fixture(scope="module")
def pure_pp_mesh():
    devs = np.array(jax.devices()[:4])
    return Mesh(devs, ("stage",))


def test_forward_matches_sequential(pp_mesh):
    params = init_stage_params(_init_fn, 4, pp_mesh, seed=0)
    x = jax.random.normal(jax.random.key(1), (16, D))
    y = pipeline_apply(_stage_fn, params, x, pp_mesh, num_microbatches=8)
    ref = _sequential(params, x, 4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)


def test_pure_pp_mesh(pure_pp_mesh):
    params = init_stage_params(_init_fn, 4, pure_pp_mesh, seed=2)
    x = jax.random.normal(jax.random.key(3), (8, D))
    y = pipeline_apply(_stage_fn, params, x, pure_pp_mesh,
                       data_axis=None, num_microbatches=4)
    ref = _sequential(params, x, 4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)


def test_default_microbatches_and_validation(pp_mesh):
    params = init_stage_params(_init_fn, 4, pp_mesh)
    assert num_stages(pp_mesh) == 4
    x = jax.random.normal(jax.random.key(0), (16, D))
    y = pipeline_apply(_stage_fn, params, x, pp_mesh)  # M = 4*S = 16
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_sequential(params, x, 4)), atol=1e-5)
    with pytest.raises(ValueError):
        pipeline_apply(_stage_fn, params, x[:6], pp_mesh,
                       num_microbatches=4)


def test_training_converges(pp_mesh):
    params = init_stage_params(_init_fn, 4, pp_mesh, seed=0)
    tx = optax.adam(1e-2)
    step = make_pipeline_train_step(
        _stage_fn, lambda y, t: jnp.mean((y - t) ** 2), tx, pp_mesh,
        params, num_microbatches=8)
    x = jax.random.normal(jax.random.key(1), (16, D))
    tgt = jnp.ones((16, D)) * 0.3
    carry = (params, tx.init(params))
    losses = []
    for _ in range(20):
        carry, m = step(carry, (x, tgt))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.5
