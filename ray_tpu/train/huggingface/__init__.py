from ray_tpu.train.huggingface.transformers_trainer import (
    TransformersTrainer, prepare_trainer)

__all__ = ["TransformersTrainer", "prepare_trainer"]
