"""AccelerateTrainer (reference: python/ray/train/huggingface/
accelerate/accelerate_trainer.py — runs a HF `accelerate`-driven loop on
each train worker; the torch backend's process group doubles as
accelerate's).

Workers call ``accelerate.Accelerator()`` inside their loop; env vars set
by the torch backend rendezvous (RANK/WORLD_SIZE/MASTER_ADDR) are what
accelerate reads, so no extra config plumbing is needed on this image's
CPU/gloo path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.torch.config import TorchConfig
from ray_tpu.train.torch.torch_trainer import TorchTrainer


class AccelerateTrainer(TorchTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict], None],
        *,
        train_loop_config: Optional[Dict] = None,
        accelerate_config: Optional[Dict] = None,
        torch_config: Optional[TorchConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        try:
            import accelerate  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "AccelerateTrainer requires the `accelerate` package"
            ) from e
        cfg = dict(train_loop_config or {})
        if accelerate_config:
            cfg["_accelerate_config"] = accelerate_config
        super().__init__(
            train_loop_per_worker,
            train_loop_config=cfg,
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint,
            datasets=datasets,
        )


class LightningTrainer(TorchTrainer):
    """Gated stub: `lightning` is not in this image's baked package set
    (reference: train/lightning/lightning_trainer.py + the
    RayDDPStrategy/RayFSDPStrategy utilities)."""

    def __init__(self, *args, **kwargs):
        try:
            import lightning  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "LightningTrainer requires `lightning`, which is not "
                "installed in this environment. Use TorchTrainer with a "
                "plain torch loop, or JaxTrainer on TPU.") from e
        super().__init__(*args, **kwargs)
