"""AlphaZero — MCTS-guided policy iteration (reference:
rllib/algorithms/alpha_zero/ (torch, externalized to rllib_contrib in the
snapshot; Silver 2017): PUCT tree search over a *state-settable* env
produces visit-count policy targets; the network is trained to match the
search policy and predict the episode outcome).

Single-player, perfect-information, deterministic envs (the reference's
AlphaZero makes the same assumption): the env must expose ``get_state()``
/ ``set_state(state)`` so the search can branch from arbitrary nodes, and
may expose an ``action_mask()`` for legality. Self-play workers are plain
actors running the search on CPU; the policy/value net is the standard
catalog module (its ``logits`` head is the prior, its ``vf`` head the
leaf value), trained with a jitted cross-entropy + value-MSE step.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children", "state",
                 "obs", "reward", "done", "mask")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: Dict[int, "_Node"] = {}
        self.state = None
        self.obs = None
        self.reward = 0.0
        self.done = False
        self.mask: Optional[np.ndarray] = None

    @property
    def value(self) -> float:
        return self.value_sum / self.visits if self.visits else 0.0


class MCTS:
    """PUCT search (Silver 2017 Eq. 2) with Dirichlet root noise. The env
    is used as its own model through get_state/set_state."""

    def __init__(self, env, predict, *, num_simulations: int = 50,
                 c_puct: float = 1.5, gamma: float = 0.997,
                 dirichlet_alpha: float = 0.3,
                 dirichlet_eps: float = 0.25,
                 rng: Optional[np.random.Generator] = None):
        self.env = env
        self.predict = predict  # obs[1, D] -> (priors[A], value)
        self.num_simulations = num_simulations
        self.c_puct = c_puct
        self.gamma = gamma
        self.dirichlet_alpha = dirichlet_alpha
        self.dirichlet_eps = dirichlet_eps
        self.rng = rng or np.random.default_rng()

    def _mask_of(self) -> Optional[np.ndarray]:
        fn = getattr(self.env, "action_mask", None)
        return None if fn is None else np.asarray(fn(), bool)

    def _expand(self, node: _Node, obs) -> float:
        priors, value = self.predict(np.asarray(obs, np.float32)[None])
        priors = np.asarray(priors, np.float64)
        node.mask = self._mask_of()
        if node.mask is not None:
            priors = np.where(node.mask, priors, 0.0)
            total = priors.sum()
            priors = (priors / total if total > 0
                      else node.mask / node.mask.sum())
        for a, p in enumerate(priors):
            if node.mask is None or node.mask[a]:
                node.children[a] = _Node(float(p))
        return float(value)

    def _select_child(self, node: _Node) -> Tuple[int, _Node]:
        sqrt_n = math.sqrt(node.visits)
        best, best_score = None, -np.inf
        for a, child in node.children.items():
            u = self.c_puct * child.prior * sqrt_n / (1 + child.visits)
            score = child.reward + self.gamma * child.value + u \
                if child.visits else u
            if score > best_score:
                best, best_score = (a, child), score
        return best

    def search(self, root_obs) -> np.ndarray:
        """Visit-count distribution over actions after the simulations."""
        root = _Node(0.0)
        root.state = self.env.get_state()
        self._expand(root, root_obs)
        root.visits = 1
        if self.dirichlet_eps > 0 and root.children:
            noise = self.rng.dirichlet(
                [self.dirichlet_alpha] * len(root.children))
            for (a, child), n in zip(root.children.items(), noise):
                child.prior = ((1 - self.dirichlet_eps) * child.prior
                               + self.dirichlet_eps * n)
        for _ in range(self.num_simulations):
            node, path = root, [root]
            # ---- select down to an unexpanded edge
            while node.children:
                action, child = self._select_child(node)
                if child.visits == 0 and child.state is None:
                    # materialize the transition once
                    self.env.set_state(node.state)
                    obs, rew, term, trunc, _ = self.env.step(action)
                    child.state = self.env.get_state()
                    child.obs = np.asarray(obs, np.float32)
                    child.reward = float(rew)
                    child.done = bool(term or trunc)
                node, path = child, path + [child]
                if node.done or node.visits == 0:
                    break
            # ---- expand + evaluate
            if node.done:
                leaf_value = 0.0
            else:
                self.env.set_state(node.state)
                leaf_value = self._expand(node, node.obs)
            # ---- backup (discounted through edge rewards)
            value = leaf_value
            for n in reversed(path):
                n.visits += 1
                n.value_sum += value
                value = n.reward + self.gamma * value
        counts = np.zeros(self.env.action_space.n, np.float64)
        for a, child in root.children.items():
            counts[a] = child.visits
        total = counts.sum()
        return (counts / total if total > 0 else
                np.ones_like(counts) / len(counts)).astype(np.float32)


class SelfPlayWorker:
    """One env + one search per actor; plays whole episodes and returns
    (obs, search-policy, outcome) training tuples."""

    def __init__(self, env_maker, module_spec, config: Dict, seed: int):
        self.env = env_maker()
        self.module = module_spec.build()
        self.config = config
        self.rng = np.random.default_rng(seed)
        self._jit_forward = jax.jit(self.module.forward)

    def play(self, weights, num_episodes: int) -> Dict:
        cfg = self.config

        def predict(obs):
            out = self._jit_forward(weights, obs)
            priors = jax.nn.softmax(out["logits"][0])
            return np.asarray(priors), float(out["vf"][0])

        obs_rows: List[np.ndarray] = []
        pi_rows: List[np.ndarray] = []
        z_rows: List[float] = []
        returns = []
        env_steps = 0
        for _ in range(num_episodes):
            obs, _ = self.env.reset()
            mcts = MCTS(self.env, predict,
                        num_simulations=cfg["num_simulations"],
                        c_puct=cfg["c_puct"], gamma=cfg["gamma"],
                        dirichlet_alpha=cfg["dirichlet_alpha"],
                        dirichlet_eps=cfg["dirichlet_eps"], rng=self.rng)
            ep_obs, ep_pi, ep_rew = [], [], []
            done = False
            t = 0
            while not done:
                root_state = self.env.get_state()
                pi = mcts.search(np.asarray(obs, np.float32))
                self.env.set_state(root_state)
                if t < cfg["temperature_moves"]:
                    action = int(self.rng.choice(len(pi), p=pi))
                else:
                    action = int(pi.argmax())
                ep_obs.append(np.asarray(obs, np.float32))
                ep_pi.append(pi)
                obs, rew, term, trunc, _ = self.env.step(action)
                ep_rew.append(float(rew))
                done = term or trunc
                t += 1
                env_steps += 1
            # outcome targets: discounted return-to-go from each move
            z = 0.0
            zs = np.empty(len(ep_rew), np.float32)
            for i in reversed(range(len(ep_rew))):
                z = ep_rew[i] + cfg["gamma"] * z
                zs[i] = z
            obs_rows += ep_obs
            pi_rows += ep_pi
            z_rows += zs.tolist()
            returns.append(float(np.sum(ep_rew)))
        return {
            "obs": np.stack(obs_rows),
            "pi": np.stack(pi_rows),
            "z": np.asarray(z_rows, np.float32),
            "episode_returns": returns,
            "env_steps": env_steps,
        }

    def stop(self):
        return True


class AlphaZeroLearner:
    """CE(search policy, net policy) + MSE(outcome, net value)."""

    def __init__(self, module_spec, config: Dict, use_mesh: bool = True):
        self.module = module_spec.build()
        self.config = config
        self.params = self.module.init(
            jax.random.key(config.get("seed", 0)))
        self.tx = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.tx.init(self.params)

        def step(params, opt_state, batch):
            def losses(p):
                out = self.module.forward(p, batch["obs"])
                logp = jax.nn.log_softmax(out["logits"])
                policy_loss = -jnp.mean(
                    jnp.sum(batch["pi"] * logp, axis=-1))
                value_loss = jnp.mean((out["vf"] - batch["z"]) ** 2)
                total = policy_loss + \
                    self.config.get("vf_coeff", 1.0) * value_loss
                return total, (policy_loss, value_loss)

            (loss, (pl, vl)), grads = jax.value_and_grad(
                losses, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, pl, vl

        self._step = jax.jit(step)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, self.opt_state, loss, pl, vl = self._step(
            self.params, self.opt_state, batch)
        return {"total_loss": float(loss), "policy_loss": float(pl),
                "value_loss": float(vl)}

    def get_weights(self):
        return self.params

    def set_weights(self, weights) -> None:
        self.params = weights

    def get_state(self) -> Dict:
        return {"params": self.params, "opt_state": self.opt_state}

    def set_state(self, state: Dict) -> None:
        self.params = state["params"]
        self.opt_state = state["opt_state"]


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or AlphaZero)
        self.num_simulations = 50
        self.c_puct = 1.5
        self.dirichlet_alpha = 0.3
        self.dirichlet_eps = 0.25
        self.temperature_moves = 8  # sample ~ visit counts this long
        self.episodes_per_worker = 2
        self.sgd_steps_per_iter = 8
        self.replay_capacity = 20_000
        self.vf_coeff = 1.0
        self.num_env_runners = 2

    def _training_keys(self):
        return {"num_simulations", "c_puct", "dirichlet_alpha",
                "dirichlet_eps", "temperature_moves",
                "episodes_per_worker", "sgd_steps_per_iter",
                "replay_capacity", "vf_coeff"}

    def mcts_config_dict(self) -> Dict:
        return {"num_simulations": self.num_simulations,
                "c_puct": self.c_puct, "gamma": self.gamma,
                "dirichlet_alpha": self.dirichlet_alpha,
                "dirichlet_eps": self.dirichlet_eps,
                "temperature_moves": self.temperature_moves}


class AlphaZero(Algorithm):
    learner_cls = AlphaZeroLearner

    @classmethod
    def get_default_config(cls):
        return AlphaZeroConfig(algo_class=cls)

    def setup(self, _config) -> None:
        cfg = self.config = self._algo_config
        self._module_spec = cfg.module_spec()
        if not self._module_spec.discrete:
            raise ValueError("AlphaZero needs a discrete action space")
        probe = cfg.make_env()()
        for attr in ("get_state", "set_state"):
            if not callable(getattr(probe, attr, None)):
                raise ValueError(
                    f"AlphaZero env must implement {attr}() — the search "
                    "uses the env as its own model")
        self.learner = AlphaZeroLearner(
            self._module_spec,
            {"lr": cfg.lr, "seed": cfg.seed, "vf_coeff": cfg.vf_coeff})
        worker_cls = ray_tpu.remote(SelfPlayWorker).options(
            resources={"CPU": 1})
        self.workers = [
            worker_cls.remote(cfg.make_env(), self._module_spec,
                              cfg.mcts_config_dict(), cfg.seed + i)
            for i in range(max(1, cfg.num_env_runners))]
        self._replay: Dict[str, np.ndarray] = {}
        self._np_rng = np.random.default_rng(cfg.seed)
        self._episode_returns: List[float] = []
        self._total_env_steps = 0

    def _append_replay(self, batch: Dict) -> None:
        cap = self.config.replay_capacity
        for key in ("obs", "pi", "z"):
            prev = self._replay.get(key)
            rows = batch[key] if prev is None else \
                np.concatenate([prev, batch[key]])
            self._replay[key] = rows[-cap:]

    def training_step(self) -> Dict:
        cfg = self.config
        w_ref = ray_tpu.put(self.learner.get_weights())
        samples = ray_tpu.get(
            [w.play.remote(w_ref, cfg.episodes_per_worker)
             for w in self.workers], timeout=1200)
        steps_this_iter = 0
        for s in samples:
            self._append_replay(s)
            self._episode_returns += s["episode_returns"]
            steps_this_iter += s["env_steps"]
            self._total_env_steps += s["env_steps"]
        n = len(self._replay["obs"])
        metrics: Dict = {}
        for _ in range(cfg.sgd_steps_per_iter):
            idx = self._np_rng.integers(
                0, n, min(cfg.train_batch_size, n))
            metrics = self.learner.update({
                "obs": self._replay["obs"][idx],
                "pi": self._replay["pi"][idx],
                "z": self._replay["z"][idx]})
        metrics.update({
            "env_steps_this_iter": steps_this_iter,
            "replay_rows": n,
        })
        return metrics

    def get_weights(self):
        return self.learner.get_weights()

    def compute_single_action(self, obs, explore: bool = False):
        out = self._module_spec.build().forward(
            self.learner.get_weights(), np.asarray(obs, np.float32)[None])
        return int(np.asarray(out["logits"])[0].argmax())

    def cleanup(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.get(w.stop.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    # ----------------------------------------------------------- checkpoint
    def save_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "az_state.pkl"), "wb") as f:
            pickle.dump({"learner": jax.device_get(
                self.learner.get_state()),
                "episode_returns": self._episode_returns,
                "total_env_steps": self._total_env_steps}, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "az_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.set_state(state["learner"])
        self._episode_returns = state["episode_returns"]
        self._total_env_steps = state["total_env_steps"]
