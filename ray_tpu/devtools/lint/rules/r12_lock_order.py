"""R12 — lock-order deadlock detection over the interprocedural graph.

Invariant: the project-wide lock-*order* graph (lock A held while lock B
is acquired, directly or through any resolvable call chain) must be
acyclic, and no plain ``Lock`` may be acquired from both the event loop
and GC context.

Motivating bugs: the PR 5 MemoryStore deadlock was an *ordering* bug as
much as a reentrancy one (store lock inside refcount lock on one path,
the reverse on the GC path); PR 17's ``LineageLedger`` had to hand-roll
its evict-outside-the-lock discipline precisely because ledger-lock →
store-lock nests on the retain path. R1 sees single locks; R12 sees
pairs.

Two checks:

- **Cycles**: every ordering edge inside a strongly-connected component
  of ≥2 locks is flagged at its witness site, naming the reverse-order
  witness. Two locks taken in opposite orders on any two reachable paths
  deadlock the moment both paths run concurrently.
- **Loop/GC split**: a plain (non-reentrant) ``Lock`` acquired both in
  loop-affine code and in ``__del__``/weakref context lacks the R1 RLock
  remedy — the collector can fire the destructor on the loop thread
  mid-critical-section. Flagged at the loop-side site (R1 flags the
  GC-side one), so each carries its own justification or fix.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .. import concurrency
from ..callgraph import ProjectIndex
from ..model import Violation

RULE_ID = "R12"
SUMMARY = ("lock-order cycle (two locks acquired in opposite orders on "
           "reachable paths) or plain Lock shared between event-loop "
           "and GC context — deadlock by ordering")


def _site(e: concurrency.OrderEdge) -> str:
    return (f"{e.fn.info.module.relpath}:"
            f"{getattr(e.node, 'lineno', 0)} in '{e.fn.info.qualname}'")


def check(index: ProjectIndex) -> List[Violation]:
    conc = concurrency.get(index)
    out: List[Violation] = []

    for comp in conc.lock_sccs():
        members = set(comp)
        for (a, b) in sorted(conc.edges):
            if a not in members or b not in members:
                continue
            e = conc.edges[(a, b)]
            rev = conc.edges.get((b, a))
            how = ""
            if e.via is not None:
                chain = conc.explain_path(e.via, b)
                how = (f" via the call chain "
                       f"{' -> '.join([e.fn.info.qualname] + chain)}")
            rev_txt = (f"the reverse order is taken at {_site(rev)}"
                       if rev is not None else
                       f"a reverse path exists inside the cycle "
                       f"{{{', '.join(comp)}}}")
            out.append(e.fn.info.module.violation(
                RULE_ID, e.node,
                f"lock-order cycle: '{a}' is held here while acquiring "
                f"'{b}'{how}, but {rev_txt} — two threads entering "
                f"these paths concurrently deadlock; pick one global "
                f"order or drop to a single lock"))

    # plain Lock acquired in both loop-affine and GC-affine code
    acquires: Dict[str, List[Tuple]] = {}
    for ref in sorted(conc.fns):
        fn = conc.fns[ref]
        doms = conc.domains.get(ref, set())
        for decl, node, _held in fn.acquires:
            acquires.setdefault(decl.id, []).append((fn, node, doms))
    for lock_id in sorted(acquires):
        decl = conc.lock_decls.get(lock_id)
        if decl is None or decl.kind != "Lock":
            continue
        sites = acquires[lock_id]
        loop_sites = [s for s in sites if "loop" in s[2]]
        gc_sites = [s for s in sites if "gc" in s[2]]
        if not loop_sites or not gc_sites:
            continue
        fn, node, _doms = loop_sites[0]
        gfn, gnode, _g = gc_sites[0]
        out.append(fn.info.module.violation(
            RULE_ID, node,
            f"plain Lock '{lock_id}' (declared {decl.relpath}:"
            f"{decl.line}) is acquired on the event loop here and in "
            f"GC context at {gfn.info.module.relpath}:"
            f"{getattr(gnode, 'lineno', 0)} in '{gfn.info.qualname}' "
            f"without the R1 RLock remedy — a destructor firing on the "
            f"loop thread mid-critical-section deadlocks; use RLock or "
            f"defer the GC-path work off-lock"))
    return out
