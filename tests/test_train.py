"""JaxTrainer tests (reference analog: python/ray/train/tests/
test_data_parallel_trainer.py + torch backend tests, JAX-native)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint, CheckpointConfig, FailureConfig, JaxConfig, JaxTrainer,
    RunConfig, ScalingConfig)


@pytest.fixture(scope="module")
def ray4(tmp_path_factory):
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_jax_trainer_allreduce_sgd(ray4, tmp_path):
    """2 workers run a jitted SGD step; grads sync via the collective group
    (DDP-style DCN fallback path)."""

    def loop(config):
        import jax
        import jax.numpy as jnp

        from ray_tpu.util import collective as col

        ctx = train.get_context()
        assert ctx.get_world_size() == 2
        rank = ctx.get_world_rank()

        # y = 2x + 1 fit with per-worker disjoint data
        rng = np.random.RandomState(rank)
        x = rng.rand(64).astype(np.float32)
        y = 2 * x + 1

        w = jnp.zeros(()); b = jnp.zeros(())

        @jax.jit
        def grads(w, b, x, y):
            def loss(w, b):
                pred = w * x + b
                return jnp.mean((pred - y) ** 2)

            return jax.grad(loss, argnums=(0, 1))(w, b)

        lr = 0.5
        for step in range(config["steps"]):
            gw, gb = grads(w, b, x, y)
            gw = col.allreduce(np.asarray(gw), group_name="train_default") / 2
            gb = col.allreduce(np.asarray(gb), group_name="train_default") / 2
            w = w - lr * gw
            b = b - lr * gb
            train.report({"step": step, "w": float(w), "b": float(b)})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"steps": 60},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="sgd", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert abs(result.metrics["w"] - 2.0) < 0.15
    assert abs(result.metrics["b"] - 1.0) < 0.15


def test_jax_trainer_checkpointing(ray4, tmp_path):
    def loop(config):
        import tempfile

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 3):
            if ctx.get_world_rank() == 0:
                c = Checkpoint.from_dict({"step": step})
            else:
                c = None
            train.report({"step": step}, checkpoint=c)

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="ckpt", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=2)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["step"] == 2
    # top-K retention: only 2 checkpoint dirs remain
    trial = result.path
    kept = [d for d in os.listdir(trial) if d.startswith("checkpoint_")]
    assert len(kept) == 2

    # resume from the returned checkpoint: loop continues past step 2
    trainer2 = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ckpt2", storage_path=str(tmp_path)),
        resume_from_checkpoint=result.checkpoint,
    )
    result2 = trainer2.fit()
    assert result2.error is None


def test_jax_trainer_worker_failure_restarts(ray4, tmp_path):
    marker = str(tmp_path / "failed_once")

    def loop(config):
        import os

        ctx = train.get_context()
        if ctx.get_world_rank() == 0 and not os.path.exists(config["marker"]):
            open(config["marker"], "w").close()
            raise RuntimeError("injected failure")
        train.report({"ok": 1})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="ft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics == {"ok": 1}


def test_jax_trainer_failure_exhausted(ray4, tmp_path):
    def loop(config):
        raise ValueError("always fails")

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="fail", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "always fails" in str(result.error)


def test_pytree_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from ray_tpu.train import load_pytree, save_pytree

    tree = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones(3),
            "nested": {"s": jnp.zeros(())}}
    save_pytree(tree, str(tmp_path / "ck"))
    back = load_pytree(str(tmp_path / "ck"))
    np.testing.assert_allclose(back["w"], np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(back["nested"]["s"], 0.0)


def test_uneven_report_counts(ray4, tmp_path):
    """Ranks reporting different numbers of times must not wedge the
    result-polling barrier (DONE workers are not re-polled)."""

    def loop(config):
        ctx = train.get_context()
        n = 3 if ctx.get_world_rank() == 0 else 1
        for i in range(n):
            train.report({"i": i, "rank": ctx.get_world_rank()})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="uneven", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["i"] == 2


def test_torch_trainer_ddp_gloo(ray4):
    """TorchTrainer parity path (reference: torch/config.py:129) — real
    torch.distributed gloo process group across worker actors, DDP-wrapped
    model, allreduced gradients."""
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist
        import torch.nn as nn

        from ray_tpu import train as rt_train
        from ray_tpu.train.torch import prepare_model

        torch.manual_seed(0)
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        x = torch.randn(64, 4)
        y = x.sum(dim=1, keepdim=True)
        for _ in range(10):
            loss = ((model(x) - y) ** 2).mean()
            opt.zero_grad()
            loss.backward()
            opt.step()
        # DDP => identical weights on every rank after allreduce
        w0 = model.module.weight if hasattr(model, "module") else model.weight
        rt_train.report({"loss": float(loss),
                         "world": dist.get_world_size(),
                         "w_sum": float(w0.sum())})

    result = TorchTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["world"] == 2
    assert result.metrics["loss"] < 1.0
