"""CQL — conservative Q-learning for offline RL (reference:
rllib/algorithms/cql/cql.py + cql_torch_learner: SAC machinery plus a
conservative penalty pushing Q down on out-of-distribution actions and up
on dataset actions; Kumar 2020, the CQL(H) variant).

Data source is logged JSONL transitions (offline/json_io.py) with
``obs, actions, rewards, next_obs, dones``; there are no env runners.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac.sac import (
    SAC, SACConfig, SACLearner, SACModuleSpec)
from ray_tpu.rllib.offline import JsonReader


class CQLLearner(SACLearner):
    def _losses(self, params, target_params, batch, k1, k2):
        # independent subkeys: SAC's target-action sampling must not share
        # noise with the CQL proposal actions
        k_sac, kr, kp, kn = jax.random.split(k1, 4)
        total, metrics = super()._losses(params, target_params, batch,
                                         k_sac, k2)
        cfg = self.config
        n = cfg.get("cql_n_actions", 4)
        cql_alpha = cfg.get("cql_alpha", 1.0)
        obs, next_obs = batch["obs"], batch["next_obs"]
        B = obs.shape[0]
        act_dim = self.module.spec.action_dim
        # uniform proposals in the squashed action box
        rand_a = jax.random.uniform(kr, (n, B, act_dim), minval=-1.0,
                                    maxval=1.0)
        log_unif = -act_dim * jnp.log(2.0)  # density of U[-1,1]^d
        # current-policy proposals at s and s' (importance-corrected)
        pi_a, pi_logp, _ = jax.vmap(
            lambda k: self.module.pi(params, obs, k))(
                jax.random.split(kp, n))
        nxt_a, nxt_logp, _ = jax.vmap(
            lambda k: self.module.pi(params, next_obs, k))(
                jax.random.split(kn, n))

        def cat_q(q_key):
            def q_of(a_batch, o):
                x = jnp.concatenate([o, a_batch], axis=-1)
                return self.module._tower(params[q_key], x)[..., 0]

            q_rand = jax.vmap(lambda a: q_of(a, obs))(rand_a) - log_unif
            q_pi = jax.vmap(lambda a: q_of(a, obs))(pi_a) - \
                jax.lax.stop_gradient(pi_logp)
            q_nxt = jax.vmap(lambda a: q_of(a, next_obs))(nxt_a) - \
                jax.lax.stop_gradient(nxt_logp)
            cat = jnp.concatenate([q_rand, q_pi, q_nxt], axis=0)
            return jax.scipy.special.logsumexp(cat, axis=0)

        q1_data, q2_data = self.module.q(params, obs, batch["actions"])
        gap1 = jnp.mean(cat_q("q1") - q1_data)
        gap2 = jnp.mean(cat_q("q2") - q2_data)
        cql_loss = cql_alpha * (gap1 + gap2)
        metrics["cql_loss"] = cql_loss
        metrics["cql_gap"] = 0.5 * (gap1 + gap2)
        return total + cql_loss, metrics


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or CQL)
        self.offline_data: Optional[str] = None
        self.cql_alpha = 1.0
        self.cql_n_actions = 4
        self.dataset_epochs_per_iter = 1
        self.num_env_runners = 0
        self.obs_dim: Optional[int] = None
        self.action_dim: Optional[int] = None

    def _training_keys(self):
        return super()._training_keys() | {
            "offline_data", "cql_alpha", "cql_n_actions",
            "dataset_epochs_per_iter", "obs_dim", "action_dim"}

    def offline(self, *, offline_data: str) -> "CQLConfig":
        self.offline_data = offline_data
        return self

    def learner_config_dict(self) -> Dict:
        d = super().learner_config_dict()
        d.update({"cql_alpha": self.cql_alpha,
                  "cql_n_actions": self.cql_n_actions})
        return d

    def module_spec(self) -> SACModuleSpec:
        if self.obs_dim is not None and self.action_dim is not None:
            return SACModuleSpec(
                obs_dim=self.obs_dim, action_dim=self.action_dim,
                hiddens=tuple(self.model.get("hiddens", (256, 256))),
                activation=self.model.get("activation", "relu"))
        return super().module_spec()


class CQL(Algorithm):
    learner_cls = CQLLearner

    @classmethod
    def get_default_config(cls):
        return CQLConfig(algo_class=cls)

    def setup(self, _config) -> None:
        cfg = self._algo_config
        if not cfg.offline_data:
            raise ValueError("CQL requires config.offline(offline_data=...)")
        super().setup(_config)
        self.reader = JsonReader(cfg.offline_data, seed=cfg.seed)
        full = self.reader.concat_all()
        need = {"obs", "actions", "rewards", "next_obs", "dones"}
        if not need <= set(full):
            raise ValueError(f"CQL offline data needs {sorted(need)}, "
                             f"got {sorted(full.keys())}")

    def training_step(self) -> Dict:
        cfg = self.config
        learner = self.learner_group.local_learner()
        full = self.reader.concat_all()
        n = len(full["obs"])
        steps = max(1, int(cfg.dataset_epochs_per_iter * n
                           / cfg.train_batch_size))
        metrics: Dict = {}
        for _ in range(steps):
            b = self.reader.sample(cfg.train_batch_size)
            metrics = learner.update({
                "obs": b["obs"].astype(np.float32),
                "actions": b["actions"].astype(np.float32),
                "rewards": b["rewards"].astype(np.float32),
                "next_obs": b["next_obs"].astype(np.float32),
                "dones": b["dones"].astype(np.float32),
            })
        metrics["env_steps_this_iter"] = 0
        metrics["dataset_rows"] = n
        return metrics
