"""Tune stoppers / loggers / HyperBand / gated searchers (reference:
python/ray/tune/tests/test_trial_scheduler.py + tests of tune/stopper and
tune/logger)."""

import json
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


# ----------------------------------------------------------------- stoppers
def test_maximum_iteration_stopper():
    s = tune.MaximumIterationStopper(3)
    assert not s("t", {"training_iteration": 2})
    assert s("t", {"training_iteration": 3})


def test_trial_plateau_stopper():
    s = tune.TrialPlateauStopper("loss", std=0.01, num_results=3,
                                 grace_period=3)
    assert not s("t", {"loss": 1.0})
    assert not s("t", {"loss": 0.5})
    assert not s("t", {"loss": 0.5})  # grace period just met; std high
    assert s("t", {"loss": 0.5})     # window now flat
    # different trial: independent history
    assert not s("u", {"loss": 0.5})


def test_combined_and_function_stopper():
    s = tune.CombinedStopper(
        tune.FunctionStopper(lambda tid, r: r.get("x", 0) > 10),
        tune.MaximumIterationStopper(100))
    assert s("t", {"x": 11})
    assert not s("t", {"x": 1})
    assert not s.stop_all()


def test_timeout_stopper_stops_all():
    s = tune.TimeoutStopper(-1.0)  # already expired
    assert s.stop_all()


def test_experiment_plateau_stopper():
    s = tune.ExperimentPlateauStopper("score", mode="max", top=2,
                                      patience=1)
    s("t", {"score": 1.0})
    assert not s.stop_all()  # top-k not yet full
    s("t", {"score": 1.0})
    s("t", {"score": 1.0})
    assert s.stop_all()
    # improving metric resets staleness
    s2 = tune.ExperimentPlateauStopper("score", mode="max", top=2,
                                       patience=1)
    for v in (1.0, 2.0, 3.0, 4.0):
        s2("t", {"score": v})
    assert not s2.stop_all()


# --------------------------------------------------- stopper + loggers e2e
def _train_fn(config):
    for i in range(20):
        tune.report({"score": (i + 1) * config["m"], "loss": 1.0 / (i + 1)})


def test_stopper_and_default_loggers_e2e(ray4, tmp_path):
    tuner = tune.Tuner(
        _train_fn,
        param_space={"m": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            storage_path=str(tmp_path), name="stop_e2e",
            stop=tune.MaximumIterationStopper(5)),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    for result in grid:
        assert result.metrics["training_iteration"] == 5
    # default loggers wrote result.json / progress.csv / params.json
    exp_dir = os.path.join(str(tmp_path), "stop_e2e")
    trial_dirs = [d for d in os.listdir(exp_dir)
                  if os.path.isdir(os.path.join(exp_dir, d))]
    assert trial_dirs
    found_json = found_csv = False
    for d in trial_dirs:
        p = os.path.join(exp_dir, d)
        if os.path.exists(os.path.join(p, "result.json")):
            found_json = True
            lines = [json.loads(ln) for ln in
                     open(os.path.join(p, "result.json")) if ln.strip()]
            assert len(lines) == 5
            assert "score" in lines[0]
        if os.path.exists(os.path.join(p, "progress.csv")):
            found_csv = True
    assert found_json and found_csv


def test_custom_callback_hooks(ray4, tmp_path):
    events = []

    class Recorder(tune.Callback):
        def on_trial_start(self, it, trials, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, it, trials, trial, result):
            events.append(("result", trial.trial_id))

        def on_trial_complete(self, it, trials, trial):
            events.append(("complete", trial.trial_id))

    tuner = tune.Tuner(
        _train_fn, param_space={"m": 1},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    num_samples=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="cb",
                             stop={"training_iteration": 3},
                             callbacks=[Recorder()]),
    )
    tuner.fit()
    kinds = [e[0] for e in events]
    assert "start" in kinds and "result" in kinds and "complete" in kinds


# ---------------------------------------------------------------- hyperband
def test_hyperband_stops_bad_trials(ray4, tmp_path):
    def trainable(config):
        # checkpoint-aware: HyperBand pauses/resumes trials at rung
        # barriers, so loop progress must survive the restart
        import json
        import os as _os
        import tempfile

        from ray_tpu.train._checkpoint import Checkpoint

        start = 0
        ckpt = tune.get_checkpoint()
        if ckpt:
            with open(_os.path.join(ckpt.path, "state.json")) as f:
                start = json.load(f)["i"] + 1
        for i in range(start, 30):
            d = tempfile.mkdtemp()
            with open(_os.path.join(d, "state.json"), "w") as f:
                json.dump({"i": i}, f)
            tune.report({"score": config["q"] * (i + 1)},
                        checkpoint=Checkpoint(d))

    sched = tune.HyperBandScheduler(max_t=9, reduction_factor=3)
    tuner = tune.Tuner(
        trainable,
        param_space={"q": tune.grid_search([1, 2, 3, 4, 5, 6])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched),
        run_config=RunConfig(storage_path=str(tmp_path), name="hb",
                             stop={"training_iteration": 9}),
    )
    grid = tuner.fit()
    iters = sorted(r.metrics.get("training_iteration", 0) for r in grid)
    # successive halving must have early-stopped at least one trial
    assert iters[0] < 9
    # and the best (q=6) trial must have survived to the end
    best = max(grid, key=lambda r: r.metrics.get("score", -1))
    assert best.config["q"] == 6
    assert best.metrics["training_iteration"] == 9


# --------------------------------------------------------------------- pb2
def test_pb2_gp_suggestion_unit():
    """The GP explore step must produce in-bounds configs and prefer the
    region where observed improvement was higher."""
    from ray_tpu.tune.schedulers.pb2 import PB2

    sched = PB2(metric="score", mode="max",
                hyperparam_bounds={"lr": (0.0, 1.0)}, seed=0)
    rng = np.random.default_rng(0)
    # synthetic observations: improvement grows with lr
    for _ in range(40):
        lr = float(rng.random())
        sched._X.append([lr])
        sched._y.append(lr + 0.01 * rng.standard_normal())
    cfg = sched._explore({"lr": 0.2})
    assert 0.0 <= cfg["lr"] <= 1.0
    # UCB on an increasing function should chase the upper region
    assert cfg["lr"] > 0.6, cfg


def test_pb2_requires_bounds():
    from ray_tpu.tune.schedulers.pb2 import PB2

    with pytest.raises(ValueError):
        PB2(metric="score", mode="max")


def test_pb2_e2e_improves(ray4, tmp_path):
    """Small PB2 run: trials with bad lr must get pulled toward the good
    region via exploit+GP explore."""
    def trainable(config):
        import json
        import os as _os
        import tempfile

        from ray_tpu.train._checkpoint import Checkpoint

        score = 0.0
        ckpt = tune.get_checkpoint()
        if ckpt:
            with open(_os.path.join(ckpt.path, "s.json")) as f:
                score = json.load(f)["score"]
        for _ in range(20):
            score += config["lr"]  # higher lr strictly better
            d = tempfile.mkdtemp()
            with open(_os.path.join(d, "s.json"), "w") as f:
                json.dump({"score": score}, f)
            tune.report({"score": score}, checkpoint=Checkpoint(d))

    sched = tune.PB2(perturbation_interval=4,
                     hyperparam_bounds={"lr": (0.1, 1.0)}, seed=0)
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.uniform(0.1, 1.0)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched, num_samples=4),
        run_config=RunConfig(storage_path=str(tmp_path), name="pb2",
                             stop={"training_iteration": 12}),
    ).fit()
    finals = [r.config["lr"] for r in grid]
    assert all(0.1 <= lr <= 1.0 for lr in finals)
    assert len(grid) == 4


# ----------------------------------------------------------- gated searchers
def test_gated_searchers_raise_cleanly():
    with pytest.raises(ImportError, match="optuna"):
        tune.search.OptunaSearch({"lr": tune.uniform(0, 1)})
    with pytest.raises(ImportError, match="hyperopt"):
        tune.search.HyperOptSearch({"lr": tune.uniform(0, 1)})


def test_tbx_logger_gated():
    try:
        import tensorboardX  # noqa: F401
        has = True
    except ImportError:
        try:
            from torch.utils.tensorboard import SummaryWriter  # noqa: F401
            has = True
        except ImportError:
            has = False
    if has:
        tune.TBXLoggerCallback()
    else:
        with pytest.raises(ImportError):
            tune.TBXLoggerCallback()
