"""Utility-layer tests (reference analog: python/ray/tests/test_actor_pool,
test_queue, test_metrics, util/state tests, dag tests, workflow tests)."""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue
from ray_tpu.util.queue import Empty, Full


@pytest.fixture(scope="module")
def ray4():
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------- ActorPool
def test_actor_pool_map_ordered(ray4):
    @ray_tpu.remote
    class Worker:
        def double(self, v):
            return v * 2

    pool = ActorPool([Worker.remote(), Worker.remote()])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert out == [0, 2, 4, 6, 8, 10, 12, 14]
    # unordered returns the same set
    out = sorted(pool.map_unordered(
        lambda a, v: a.double.remote(v), range(8)))
    assert out == [0, 2, 4, 6, 8, 10, 12, 14]


# -------------------------------------------------------------------- Queue
def test_queue_basics(ray4):
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    with pytest.raises(Full):
        q.put(3, block=False)
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get(block=False)
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()


def test_queue_producer_consumer(ray4):
    q = Queue()

    @ray_tpu.remote
    def produce(q, n):
        for i in range(n):
            q.put(i)
        return True

    ref = produce.remote(q, 10)
    got = [q.get(timeout=30) for _ in range(10)]
    assert got == list(range(10))
    assert ray_tpu.get(ref)
    q.shutdown()


# ------------------------------------------------------------------ metrics
def test_metrics_prometheus(ray4):
    from ray_tpu.util import metrics

    c = metrics.Counter("test_requests_total", "reqs",
                        tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = metrics.Gauge("test_inflight", "inflight")
    g.set(7)
    h = metrics.Histogram("test_latency_s", "lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    metrics.flush_now()
    text = metrics.prometheus_text()
    assert 'test_requests_total{route="/a"} 3.0' in text
    assert "test_inflight 7.0" in text
    assert 'test_latency_s_bucket{le="0.1"} 1' in text
    assert 'test_latency_s_bucket{le="+Inf"} 3' in text
    assert "test_latency_s_sum 5.55" in text


# ---------------------------------------------------------------- state API
def test_state_api(ray4):
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    class Stateful:
        def ping(self):
            return "pong"

    a = Stateful.remote()
    ray_tpu.get(a.ping.remote())
    actors = state_api.list_actors()
    assert any(x.get("class_name") == "Stateful" for x in actors)
    nodes = state_api.list_nodes()
    assert nodes and nodes[0]["state"] == "ALIVE"

    @ray_tpu.remote
    def named_task():
        return 1

    ray_tpu.get(named_task.remote())
    tasks = state_api.list_tasks()
    assert any("named_task" in t.get("name", "") for t in tasks)
    summary = state_api.summarize_actors()
    assert "Stateful" in summary
    ray_tpu.kill(a)


# ---------------------------------------------------------------------- DAG
def test_dag_bind_execute(ray4):
    from ray_tpu.dag import InputNode, MultiOutputNode

    @ray_tpu.remote
    def plus_one(x):
        return x + 1

    @ray_tpu.remote
    def times_two(x):
        return x * 2

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        a = plus_one.bind(inp)
        b = times_two.bind(inp)
        dag = add.bind(a, b)
    assert ray_tpu.get(dag.execute(10)) == 31  # (10+1) + (10*2)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(1)) == 4

    with InputNode() as inp:
        multi = MultiOutputNode([plus_one.bind(inp), times_two.bind(inp)])
    assert ray_tpu.get(multi.execute(3)) == [4, 6]


def test_dag_actor_methods(ray4):
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Accum:
        def __init__(self):
            self.total = 0

        def add(self, v):
            self.total += v
            return self.total

    acc = Accum.remote()
    with InputNode() as inp:
        dag = acc.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 5
    assert ray_tpu.get(dag.execute(3)) == 8  # actor state persists


# ----------------------------------------------------------------- workflow
def test_workflow_run_and_resume(ray4, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path))
    marker = str(tmp_path / "ran_expensive")

    @ray_tpu.remote
    def expensive(x):
        open(marker, "a").write("x")
        return x * 10

    @ray_tpu.remote
    def flaky(x, fail_marker):
        if not os.path.exists(fail_marker):
            open(fail_marker, "w").close()
            raise RuntimeError("first attempt fails")
        return x + 1

    fail_marker = str(tmp_path / "fail_once")
    with InputNode() as inp:
        dag = flaky.bind(expensive.bind(inp), fail_marker)

    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf1", args=(5,))
    assert workflow.get_status("wf1") == "FAILED"
    # resume: the expensive step is served from its checkpoint, not re-run
    out = workflow.resume("wf1", dag, args=(5,))
    assert out == 51
    assert open(marker).read() == "x"  # ran exactly once
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert {"workflow_id": "wf1", "status": "SUCCESSFUL"} in \
        workflow.list_all()


def test_workflow_run_async_and_events(ray4, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def combine(payload, x):
        return (bytes(payload), x)

    event_file = str(tmp_path / "event_payload")
    with InputNode() as inp:
        dag = combine.bind(
            workflow.wait_for_event(
                workflow.FileEventListener, event_file), inp)

    fut = workflow.run_async(dag, workflow_id="wf_evt", args=(7,))
    assert not fut.done()  # blocked on the event
    with open(event_file, "wb") as f:
        f.write(b"fired")
    payload, x = fut.result(timeout=120)
    assert payload == b"fired" and x == 7
    assert workflow.get_status("wf_evt") == "SUCCESSFUL"
    # resume does NOT wait again: the event payload was checkpointed
    os.remove(event_file)
    payload2, _ = workflow.resume("wf_evt", dag, args=(7,))
    assert payload2 == b"fired"


# ----------------------------------------------------------- job submission
def test_job_submission(ray4, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    out_file = str(tmp_path / "job_out.txt")
    job_id = client.submit_job(
        entrypoint=f"echo hello-from-job > {out_file} && echo logged-line",
        metadata={"owner": "test"})
    status = client.wait_until_finish(job_id, timeout_s=60)
    assert status == JobStatus.SUCCEEDED
    assert open(out_file).read().strip() == "hello-from-job"
    assert "logged-line" in client.get_job_logs(job_id)
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(ray4):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint="exit 3")
    assert client.wait_until_finish(job_id, 60) == JobStatus.FAILED
    assert "code 3" in client.get_job_info(job_id)["message"]


# ---------------------------------------------------------------- dashboard
def test_dashboard_rest(ray4):
    from ray_tpu.dashboard import start_dashboard

    port = start_dashboard(port=0)

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, r.read()

    status, body = get("/healthz")
    assert status == 200
    status, body = get("/api/nodes")
    nodes = json.loads(body)
    assert nodes and nodes[0]["state"] == "ALIVE"
    status, body = get("/api/cluster_status")
    data = json.loads(body)
    assert data["total"].get("CPU", 0) >= 4
    status, body = get("/metrics")
    assert status == 200


class TestCheckSerialize:
    def test_serializable_object(self, ray4):
        from ray_tpu.util.check_serialize import inspect_serializability

        ok, failures = inspect_serializability(lambda x: x + 1,
                                               print_failures=False)
        assert ok and not failures

    def test_finds_offending_closure_var(self, ray4):
        import threading

        from ray_tpu.util.check_serialize import inspect_serializability

        lock = threading.Lock()

        def f():
            return lock

        ok, failures = inspect_serializability(f, print_failures=False)
        assert not ok
        assert any("lock" in t.name for t in failures)


class TestTpuSliceHelpers:
    def test_resource_names(self):
        from ray_tpu.util.accelerators import (
            pod_slice_head_resource, pod_slice_resource)

        assert pod_slice_head_resource("v5e-64") == "TPU-v5e-64-head"
        assert pod_slice_resource("my-slice") == "my-slice"

    def test_slice_hosts(self):
        from ray_tpu.util.accelerators import slice_hosts

        n = slice_hosts("v5e-64")
        assert n is None or (isinstance(n, int) and n >= 1)

    def test_reserve_slice_fails_fast_without_head_node(self, ray4):
        from ray_tpu.util.accelerators import reserve_tpu_slice

        # no node advertises the v5e-8 head resource here: the reservation
        # must fail fast with a clean error, not wedge
        import pytest

        with pytest.raises(Exception):
            reserve_tpu_slice("v5e-8", timeout_s=2.0)

    def test_deep_nesting_still_reports_something(self, ray4):
        """Depth-cutoff must not produce a failed-but-empty verdict."""
        import threading

        from ray_tpu.util.check_serialize import inspect_serializability

        lock = threading.Lock()

        def f0():
            def f1():
                def f2():
                    def f3():
                        def f4():
                            def f5():
                                return lock
                            return f5
                        return f4
                    return f3
                return f2
            return f1

        ok, failures = inspect_serializability(f0, print_failures=False)
        assert not ok
        assert failures, "failed verdict must name at least one object"
