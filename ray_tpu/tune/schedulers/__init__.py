"""Trial schedulers (reference: python/ray/tune/schedulers/ —
TrialScheduler ABC in trial_scheduler.py, ASHA in async_hyperband.py:19,
PBT in pbt.py:221, MedianStoppingRule in median_stopping_rule.py)."""

from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler, TrialScheduler)
from ray_tpu.tune.schedulers.async_hyperband import (
    ASHAScheduler, AsyncHyperBandScheduler)
from ray_tpu.tune.schedulers.hyperband import (
    HyperBandForBOHB, HyperBandScheduler)
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.pb2 import PB2
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining

__all__ = [
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "AsyncHyperBandScheduler", "HyperBandScheduler", "HyperBandForBOHB",
    "MedianStoppingRule",
    "PopulationBasedTraining", "PB2",
]
