"""Parallel iterators (reference: python/ray/util/iter.py — from_items /
from_range / ParallelIterator with for_each/filter/batch/gather, sharded
over actors).

Each shard is an actor owning one slice of the source; transformations are
lazy per-shard programs executed where the shard lives. ``gather_sync``
round-robins shard outputs back to the driver.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, TypeVar

import ray_tpu

T = TypeVar("T")


class _ShardActor:
    def __init__(self, items: List, ops: List):
        self._items = items
        self._ops = ops
        self._it: Iterator = iter(())
        self.reset()

    def reset(self):
        def gen():
            for item in self._items:
                out = [item]
                for kind, fn in self._ops:
                    if kind == "for_each":
                        out = [fn(x) for x in out]
                    elif kind == "filter":
                        out = [x for x in out if fn(x)]
                    elif kind == "flatten":
                        out = [y for x in out for y in x]
                yield from out

        self._it = gen()
        return True

    def next_batch(self, n: int) -> List:
        return list(itertools.islice(self._it, n))


class ParallelIterator:
    def __init__(self, source_shards: List[List], ops: List = None):
        self._shards = source_shards
        self._ops = ops or []

    # ------------------------------------------------------- transformations
    def for_each(self, fn: Callable) -> "ParallelIterator":
        return ParallelIterator(self._shards, self._ops + [("for_each", fn)])

    def filter(self, fn: Callable) -> "ParallelIterator":
        return ParallelIterator(self._shards, self._ops + [("filter", fn)])

    def flatten(self) -> "ParallelIterator":
        return ParallelIterator(self._shards, self._ops + [("flatten", None)])

    def batch(self, n: int) -> "_BatchedIterator":
        """Gather-side batching (shard programs stay stateless)."""
        return _BatchedIterator(self, n)

    def num_shards(self) -> int:
        return len(self._shards)

    # --------------------------------------------------------------- gather
    def gather_sync(self, batch: int = 64) -> Iterator[Any]:
        actors = [ray_tpu.remote(_ShardActor).remote(s, self._ops)
                  for s in self._shards]
        try:
            live = list(actors)
            while live:
                refs = [a.next_batch.remote(batch) for a in live]
                results = ray_tpu.get(refs, timeout=300)
                nxt = []
                for a, chunk in zip(live, results):
                    if chunk:
                        nxt.append(a)
                        yield from chunk
                live = nxt
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    def __iter__(self) -> Iterator[Any]:
        return self.gather_sync()

    def take(self, n: int) -> List:
        out = []
        for item in self:
            out.append(item)
            if len(out) >= n:
                break
        return out


class _BatchedIterator:
    def __init__(self, parent: ParallelIterator, n: int):
        self._parent = parent
        self._n = n

    def __iter__(self):
        buf: List = []
        for item in self._parent:
            buf.append(item)
            if len(buf) == self._n:
                yield list(buf)
                buf.clear()
        if buf:
            yield buf

    def take(self, n: int) -> List:
        out = []
        for item in self:
            out.append(item)
            if len(out) >= n:
                break
        return out


def from_items(items: List[T], num_shards: int = 2) -> ParallelIterator:
    shards: List[List] = [[] for _ in range(num_shards)]
    for i, item in enumerate(items):
        shards[i % num_shards].append(item)
    return ParallelIterator(shards)


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)
