"""R10 — grow-only container in a long-lived service class.

Invariant: a ``self.``-attribute (or module-level) dict/list/set that a
resident service process only ever ADDS to is a memory leak with a
delay fuse.  Agents, the GCS and worker runtimes live for the cluster's
lifetime; a ledger keyed by object/task/worker ids that nothing ever
prunes grows with cumulative traffic, not live state.

Motivating bugs: the PR 11 agent demand ledger and pool-waiter queue
(unbounded in no-stats-polling regimes, pruned in-PR), the PR 13 GCS
task-event list (O(n) copy per overflow until it became a capped
deque), and the set_resolved resurrection leak the ISSUE 15 ref-leak
gate caught (an owned-table entry nothing could ever free again).

Detection (per module): a class with at least one ``async def`` method
containing a ``while`` loop (the resident-service marker) whose
``__init__`` assigns ``self.<name>`` an empty dict/list/set/
defaultdict/OrderedDict, where the class body then contains at least
one grow operation on ``self.<name>`` and NO shrink operation
(``pop``/``popitem``/``clear``/``remove``/``discard``/``popleft``/
``del self.<name>[...]``/wholesale reassignment outside ``__init__``).
Passing the bare container to a call (``prune(self._ledger)``) counts
as an escape and suppresses the finding — someone else may own the
pruning.  ``deque(maxlen=...)`` is bounded by construction and never
flagged.  Module-level containers are checked the same way in modules
that define such a service class.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..model import ModuleInfo, Violation

RULE_ID = "R10"
SUMMARY = ("grow-only dict/list/set in a long-lived service class — "
           "entries are added on traffic but nothing ever prunes them, "
           "so the process leaks with cumulative load; add an eviction "
           "path or bound it by construction")

_EMPTY_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                "Counter", "WeakValueDictionary"}
_GROW_METHODS = {"append", "add", "setdefault", "extend", "insert",
                 "appendleft", "update"}
_SHRINK_METHODS = {"pop", "popitem", "clear", "remove", "discard",
                   "popleft", "prune"}


def _is_empty_container(node: ast.AST) -> bool:
    """``{}`` / ``[]`` / ``set()`` / ``dict()`` / ``defaultdict(...)`` /
    ``OrderedDict()`` — an empty growable container literal/ctor."""
    if isinstance(node, ast.Dict) and not node.keys:
        return True
    if isinstance(node, ast.List) and not node.elts:
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        return name in _EMPTY_CTORS
    return False


def _is_service_class(cls: ast.ClassDef) -> bool:
    """Long-lived marker: any async method with a ``while`` loop (the
    shape of every agent/gcs/worker background service loop)."""
    for item in cls.body:
        if isinstance(item, ast.AsyncFunctionDef):
            for sub in ast.walk(item):
                if isinstance(sub, ast.While):
                    return True
    return False


class _ContainerOps:
    __slots__ = ("grow", "shrink", "escape", "decl")

    def __init__(self, decl: ast.AST):
        self.decl = decl
        self.grow = 0
        self.shrink = 0
        self.escape = 0


def _target_name(node: ast.AST, self_attr: bool) -> Optional[str]:
    """Name of the container an expression refers to: ``self.x`` (when
    self_attr) or a bare module-level ``x``."""
    if self_attr:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return None
    if isinstance(node, ast.Name):
        return node.id
    return None


def _scan_ops(tree_nodes, containers: Dict[str, _ContainerOps],
              self_attr: bool, skip: Optional[Set[ast.AST]] = None) -> None:
    """Classify every reference to a tracked container as grow / shrink /
    escape. ``skip`` holds the declaration statements themselves."""
    skip = skip or set()
    for node in tree_nodes:
        for sub in ast.walk(node):
            if sub in skip:
                continue
            # self.x[k] = v  /  x[k] = v  (grow);  del self.x[k] (shrink)
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        name = _target_name(t.value, self_attr)
                        if name in containers:
                            containers[name].grow += 1
                    else:
                        name = _target_name(t, self_attr)
                        if name in containers:
                            # wholesale reassignment outside the decl:
                            # a reset path — counts as shrink
                            containers[name].shrink += 1
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript):
                        name = _target_name(t.value, self_attr)
                        if name in containers:
                            containers[name].shrink += 1
            elif isinstance(sub, ast.Attribute):
                # any reference to self.x.pop / self.x.discard — called
                # directly OR passed as a callback
                # (task.add_done_callback(self._bg_tasks.discard)) —
                # proves a shrink path exists
                name = _target_name(sub.value, self_attr)
                if name in containers:
                    if sub.attr in _GROW_METHODS:
                        containers[name].grow += 1
                    elif sub.attr in _SHRINK_METHODS:
                        containers[name].shrink += 1
            elif isinstance(sub, ast.Call):
                # bare container passed to a call: ownership escapes
                for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                    name = _target_name(arg, self_attr)
                    if name in containers:
                        containers[name].escape += 1


def check_module(mod: ModuleInfo, index) -> List[Violation]:
    out: List[Violation] = []
    service_classes = [n for n in ast.walk(mod.tree)
                       if isinstance(n, ast.ClassDef)
                       and _is_service_class(n)]
    for cls in service_classes:
        init = next((i for i in cls.body
                     if isinstance(i, ast.FunctionDef)
                     and i.name == "__init__"), None)
        if init is None:
            continue
        containers: Dict[str, _ContainerOps] = {}
        decls: Set[ast.AST] = set()
        for stmt in ast.walk(init):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            value = stmt.value
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if value is None or not _is_empty_container(value):
                continue
            for t in targets:
                name = _target_name(t, self_attr=True)
                if name:
                    containers[name] = _ContainerOps(stmt)
                    decls.add(stmt)
        if not containers:
            continue
        _scan_ops([n for n in cls.body if n is not init] + [init],
                  containers, self_attr=True, skip=decls)
        for name, ops in containers.items():
            if ops.grow and not ops.shrink and not ops.escape:
                out.append(mod.violation(
                    RULE_ID, ops.decl,
                    f"'self.{name}' in service class '{cls.name}' is "
                    f"only ever added to ({ops.grow} grow sites, no "
                    f"pop/del/clear/maxlen anywhere in the class): a "
                    f"long-lived process leaks it with cumulative "
                    f"traffic — add an eviction/prune path, bound it, "
                    f"or justify with a disable"))
    # module-level containers, only in modules hosting a service class
    if service_classes:
        containers = {}
        decls = set()
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    _is_empty_container(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and not t.id.startswith("__"):
                        containers[t.id] = _ContainerOps(stmt)
                        decls.add(stmt)
        if containers:
            _scan_ops([n for n in mod.tree.body if n not in decls],
                      containers, self_attr=False, skip=decls)
            for name, ops in containers.items():
                if ops.grow and not ops.shrink and not ops.escape:
                    out.append(mod.violation(
                        RULE_ID, ops.decl,
                        f"module-level '{name}' is only ever added to "
                        f"({ops.grow} grow sites, no shrink op in the "
                        f"module) in a module hosting a long-lived "
                        f"service class — it leaks with cumulative "
                        f"traffic"))
    return out
